"""The TPU-native sampler: whole-generation batched rounds.

This is the TPU inversion of the reference's evaluation-parallel dynamic
samplers (``pyabc/sampler/multicore_evaluation_parallel.py::
MulticoreEvalParallelSampler`` and the redis variant): instead of worker
processes pulling scalar evaluations off a queue, each *round* evaluates a
static-shape batch of B lanes as one fused XLA program; the host loop refills
until n acceptances (mask-and-refill, SURVEY.md §7.1).

Unbiasedness: lanes carry global eval-slot ids; the accepted set is sorted by
slot id and overshoot beyond n is trimmed deterministically — exactly the
reference's sort-by-eval-index trick that makes dynamic/batched sampling
statistically equivalent to sequential sampling (§3.4, §5.2).

Batch sizing: rounds are sized predictively from the observed acceptance
rate (clamped to power-of-two buckets to bound recompilation) — the batched
analog of the reference's dynamic scheduling.
"""
from __future__ import annotations

import numpy as np

from ..core.random import round_key
from ..utils import pow2_bucket as _pow2
from .base import Sample, Sampler, exp_normalize_log_weights


class BatchedSampler(Sampler):
    """Single-host batched sampler over one device (or one jit on CPU).

    ``min_batch``/``max_batch`` bound the per-round lane count;
    ``overshoot`` is the safety factor on predictive sizing.
    """

    def __init__(self, min_batch: int = 256, max_batch: int = 1 << 17,
                 overshoot: float = 1.3, check_max_eval: bool = False,
                 fused: bool = True, max_rounds: int = 256):
        super().__init__()
        self.min_batch = int(min_batch)
        self.max_batch = int(max_batch)
        self.overshoot = float(overshoot)
        self.check_max_eval = check_max_eval
        #: fused=True runs the whole generation (refill loop included) as a
        #: single lax.while_loop device program — one dispatch per
        #: generation; False keeps the per-round host loop (debugging)
        self.fused = fused
        self.max_rounds = int(max_rounds)
        #: acceptance-rate estimate carried across generations: sizes the
        #: FIRST round of the next generation so one round usually suffices,
        #: and keeps B constant within a generation (compile reuse)
        self._rate_estimate: float | None = None
        self._last_B: int | None = None

    def _pick_B(self, n: int) -> int:
        """Power-of-two batch with WIDE hysteresis: stick with the previous
        B unless the target moved by more than 8x. Every distinct B is a
        separate XLA compile (~10s on a TPU) while extra while_loop rounds
        at a stale B cost milliseconds — recompiling to chase the
        acceptance rate is almost never worth it."""
        rate = self._rate_estimate if self._rate_estimate else 0.5
        target = _pow2(max(int(n / rate * self.overshoot), self.min_batch),
                       self.min_batch, self.max_batch)
        if (self._last_B is not None
                and self._last_B // 8 <= target <= self._last_B * 8):
            return self._last_B
        self._last_B = target
        return target

    def sample_until_n_accepted(self, n, generation_spec, t, *,
                                max_eval=np.inf, all_accepted=False,
                                ana_vars=None) -> Sample:
        ctx = generation_spec.device
        if ctx is None:
            raise RuntimeError(
                "BatchedSampler needs a device-compatible generation "
                "(JaxModel models, traceable priors/components); use "
                "SingleCoreSampler for host-only models"
            )
        mode, dyn = generation_spec.mode, generation_spec.dyn
        gen_key = generation_spec.gen_key

        if self.fused:
            return self._sample_fused(n, ctx, mode, dyn, gen_key,
                                      max_eval=max_eval,
                                      all_accepted=all_accepted)

        sample = self.sample_factory()
        chunks = []
        lanes_total = 0  # all lanes (slot-id base)
        nr_eval = 0      # valid lanes only = true model evaluations
        n_acc = 0
        r = 0
        # size B once per generation from the carried acceptance estimate and
        # keep it constant across refill rounds: one compiled program per
        # distinct B, reused across rounds AND generations
        B = self._pick_B(n)
        while n_acc < n:
            # guard on lanes_total, not valid-only nr_eval: an all-invalid
            # regime (every simulation NaN) would never advance nr_eval and
            # spin forever; max_rounds is the unconditional backstop
            if self.check_max_eval and lanes_total >= max_eval:
                break
            if r >= self.max_rounds:
                break
            res = ctx.run_round(round_key(gen_key, r), B, mode, dyn)
            if all_accepted:
                res.accepted = res.valid.copy()
                res.log_weights = np.where(res.valid, 0.0, -np.inf)
            res.slot_ids = lanes_total + np.arange(B)
            chunks.append(res)
            lanes_total += B
            nr_eval += int(res.valid.sum())
            n_acc += int(res.accepted.sum())
            r += 1
            # grow B only on repeated undershoot (keeps compile cache warm)
            rate = max(n_acc / lanes_total, 1.0 / lanes_total)
            if (n - n_acc) > rate * B:
                B = min(B * 2, self.max_batch)
        self.nr_evaluations_ = max(nr_eval, 1)
        self._rate_estimate = max(n_acc / lanes_total, 1.0 / lanes_total)

        acc_mask = np.concatenate([c.accepted for c in chunks])
        return self._finalize_rounds(sample, chunks, acc_mask, n)

    #: the fused path can dispatch a generation asynchronously and collect
    #: later — the hook ABCSMC uses for cross-generation pipelining
    supports_pipelining = True

    def dispatch(self, n, generation_spec, t, *, max_eval=np.inf,
                 all_accepted=False, speculative=None):
        """Launch the whole generation on the device WITHOUT blocking.

        Returns an opaque handle for :meth:`collect`. The TPU analog of the
        reference Redis sampler's look-ahead: while the device crunches
        generation t+1, the host persists/analyzes generation t
        (SURVEY.md §2.3 look-ahead row; here proposals are built from FINAL
        generation-t weights, so no weight correction is needed).

        ``speculative``: an eps=+inf proposal round ALREADY dispatched for
        this generation (inference.dispatch.dispatch_speculative_round) — its delayed
        host acceptance is applied now that the thresholds are final, and
        the main generation kernel only samples the SHORTFALL.
        """
        ctx = generation_spec.device
        if ctx is None:
            raise RuntimeError("dispatch() needs a device-capable generation")
        mode, dyn = generation_spec.mode, generation_spec.dyn
        # all_accepted arrives as the prior kernel with eps=+inf (calibration
        # shares the prior compile); legacy 'calibration' mode still works
        sample = self.sample_factory()
        spec_block = None
        n_target = n
        if speculative is not None:
            import jax

            fetched = jax.device_get(speculative["out"])
            self.sync_ledger.record(
                "speculative_fetch",
                sum(np.asarray(v).nbytes for v in fetched.values()),
            )
            accept, extra_lw = speculative["accept"](
                speculative["t"], fetched
            )
            B_spec = speculative["B"]
            idx = np.flatnonzero(accept)
            spec_block = {
                "ms": np.asarray(fetched["m"], np.int32)[idx],
                "thetas": np.asarray(fetched["theta"], np.float64)[idx],
                "sumstats": np.asarray(fetched["sumstats"], np.float64)[idx],
                "distances": np.asarray(fetched["distance"],
                                        np.float64)[idx],
                "log_weights": (np.asarray(fetched["log_weight"],
                                           np.float64)[idx]
                                + np.asarray(extra_lw, np.float64)[idx]),
                # negative slots: the speculative round chronologically
                # precedes every main-kernel round, and the sort-by-slot
                # trim must reflect that
                "slots": idx - B_spec,
                "n_valid": int(np.asarray(fetched["valid"], bool).sum()),
                "records": {
                    "distances": np.asarray(
                        fetched["distance"], np.float64),
                    "accepted": np.asarray(accept, bool),
                    "valid": np.asarray(fetched["valid"], bool),
                    "ms": np.asarray(fetched["m"], np.int32),
                    "thetas": np.asarray(fetched["theta"], np.float64),
                    "logqs": np.asarray(fetched.get("logq"), np.float64)
                    if "logq" in fetched else None,
                },
            }
            n_target = max(n - len(idx), 0)
            # the speculative lanes already spent evaluation budget
            max_eval = max(max_eval - B_spec, 1)
        B = self._pick_B(n)
        n_cap = _pow2(n, 64)
        rec_cap = 1
        if sample.record_rejected:
            cap = min(sample.max_nr_rejected, 8 * n_cap)
            rec_cap = _pow2(int(cap) if np.isfinite(cap) else 8 * n_cap, 256)
        max_rounds = self.max_rounds
        if self.check_max_eval and np.isfinite(max_eval):
            max_rounds = max(1, min(max_rounds, int(max_eval) // B))
        with self.tracer.span("device.dispatch", n=int(n), B=int(B)):
            out = ctx.dispatch_generation(
                generation_spec.gen_key, B, mode, dyn, n_cap=n_cap,
                rec_cap=rec_cap, max_rounds=max_rounds, n_target=n_target,
                record_proposal=(sample.record_rejected
                                 and sample.record_proposal_info),
            )
        return {"out": out, "sample": sample, "n": n, "n_cap": n_cap,
                "spec": spec_block}

    def collect(self, handle) -> Sample:
        """Block on a dispatched generation and build the Sample.

        The record-ring sum stats stay ON DEVICE (the single largest part
        of the payload; its consumer is a device-side reduction — see
        DeviceRecords); everything else is fetched in one transfer.
        """
        import jax

        out = handle["out"]
        with self.tracer.span("device.collect", n=int(handle["n"])):
            host = jax.device_get(
                {k: v for k, v in out.items() if k != "rec_sumstats"}
            )
        self.sync_ledger.record(
            "generation_collect",
            sum(np.asarray(v).nbytes for v in host.values()),
        )
        host["rec_sumstats_dev"] = out.get("rec_sumstats")
        host["rec_valid_dev"] = out.get("rec_valid")
        return self._finalize_fused(host, handle["sample"], handle["n"],
                                    handle["n_cap"],
                                    spec=handle.get("spec"))

    def _sample_fused(self, n, ctx, mode, dyn, gen_key, *, max_eval,
                      all_accepted):
        """One device dispatch for the whole generation (fused while_loop)."""
        from types import SimpleNamespace

        spec = SimpleNamespace(device=ctx, mode=mode, dyn=dyn,
                               gen_key=gen_key)
        return self.collect(self.dispatch(
            n, spec, None, max_eval=max_eval, all_accepted=all_accepted
        ))

    def _finalize_fused(self, out, sample, n, n_cap, spec=None):
        # count only valid lanes as model evaluations: proposals that failed
        # the prior-support redraws never reach the model in the reference
        # (generate_valid_proposal retries without counting), and counting
        # them skews acceptance-rate telemetry feeding adaptive schemes
        n_valid = int(out["n_valid"]) + (spec["n_valid"] if spec else 0)
        self.nr_evaluations_ = max(n_valid, 1)
        k = min(int(out["n_acc"]), n_cap, n)
        ms = np.asarray(out["m"][:k], np.int32)
        thetas = np.asarray(out["theta"][:k], np.float64)
        distances = np.asarray(out["distance"][:k], np.float64)
        sumstats = np.asarray(out["sumstats"][:k], np.float64)
        log_w = np.asarray(out["log_weight"][:k], np.float64)
        slots = np.asarray(out["slot"][:k])
        if spec is not None and len(spec["slots"]):
            # speculative round accepted first (negative slots): merge at
            # the RAW log-weight level so relative weighting stays exact
            ms = np.concatenate([spec["ms"], ms])
            thetas = np.concatenate([spec["thetas"], thetas])
            distances = np.concatenate([spec["distances"], distances])
            sumstats = np.concatenate([spec["sumstats"], sumstats])
            log_w = np.concatenate([spec["log_weights"], log_w])
            slots = np.concatenate([spec["slots"], slots])
        weights = exp_normalize_log_weights(log_w)
        sample.set_accepted(
            ms=ms, thetas=thetas, weights=weights, distances=distances,
            sumstats=sumstats, proposal_ids=slots,
        )
        sample.trim(n)
        if sample.record_rejected:
            from .base import DeviceRecords

            import jax

            valid = np.asarray(out["rec_valid"], bool)
            rec_dev = out.get("rec_sumstats_dev")
            if "rec_logq" in out:
                prop_kw = dict(
                    ms=np.asarray(out["rec_m"], np.int32)[valid],
                    thetas=np.asarray(out["rec_theta"], np.float64)[valid],
                    proposal_pds=np.exp(np.asarray(
                        out["rec_logq"], np.float64))[valid],
                )
            else:
                prop_kw = {}
            if np.isfinite(sample.max_nr_rejected) or rec_dev is None:
                # a finite cap has reference accepted-first retention
                # semantics that set_all_records enforces (on EVERY record
                # array, keeping proposal info row-aligned) — fetch the ring
                ss = out.get("rec_sumstats")
                if ss is None:
                    ss = jax.device_get(rec_dev)
                    self.sync_ledger.record(
                        "record_ring_fetch", np.asarray(ss).nbytes
                    )
                sample.set_all_records(
                    sumstats=np.asarray(ss, np.float64)[valid],
                    distances=np.asarray(
                        out["rec_distance"], np.float64)[valid],
                    accepted=np.asarray(out["rec_accepted"], bool)[valid],
                    **prop_kw,
                )
            else:
                sample.all_distances = np.asarray(
                    out["rec_distance"], np.float64
                )[valid]
                sample.all_accepted = np.asarray(
                    out["rec_accepted"], bool
                )[valid]
                sample.device_records = DeviceRecords(
                    rec_dev, out.get("rec_valid_dev", None),
                    scale=out.get("rec_scale"),
                    sync_ledger=self.sync_ledger,
                )
                if prop_kw:
                    sample.all_ms = prop_kw["ms"]
                    sample.all_thetas = prop_kw["thetas"]
                    sample.all_proposal_pds = prop_kw["proposal_pds"]
            if spec is not None:
                # speculative lanes are real evaluations: prepend their
                # records (distance/accepted + proposal info) so adaptive
                # schemes (e.g. the AcceptanceRateScheme) see them; their
                # sumstats are not folded into the device ring — configs
                # that reduce the ring (adaptive distances) never speculate
                r = spec["records"]
                rv = r["valid"]
                def _pre(a, b):
                    return np.concatenate([a[rv], b]) if b is not None \
                        else a[rv]
                if sample.all_distances is not None:
                    sample.all_distances = _pre(
                        r["distances"], sample.all_distances)
                    sample.all_accepted = _pre(
                        r["accepted"], sample.all_accepted)
                if sample.all_proposal_pds is not None \
                        and r["logqs"] is not None:
                    sample.all_ms = _pre(r["ms"], sample.all_ms)
                    sample.all_thetas = _pre(r["thetas"], sample.all_thetas)
                    sample.all_proposal_pds = np.concatenate(
                        [np.exp(r["logqs"][rv]), sample.all_proposal_pds])
        n_acc_total = int(out["n_acc"]) + (
            len(spec["slots"]) if spec is not None else 0)
        self._rate_estimate = max(
            n_acc_total / max(self.nr_evaluations_, 1),
            1.0 / max(self.nr_evaluations_, 1),
        )
        return sample

    def _finalize_rounds(self, sample, chunks, acc_mask, n):
        ms = np.concatenate([c.ms for c in chunks])[acc_mask]
        thetas = np.concatenate([c.thetas for c in chunks])[acc_mask]
        sumstats = np.concatenate([c.sumstats for c in chunks])[acc_mask]
        distances = np.concatenate([c.distances for c in chunks])[acc_mask]
        log_w = np.concatenate([c.log_weights for c in chunks])[acc_mask]
        slots = np.concatenate([c.slot_ids for c in chunks])[acc_mask]
        weights = exp_normalize_log_weights(log_w)
        sample.set_accepted(
            ms=ms, thetas=thetas, weights=weights, distances=distances,
            sumstats=sumstats, proposal_ids=slots,
        )
        sample.trim(n)
        if sample.record_rejected:
            valid_mask = np.concatenate([c.valid for c in chunks])
            if sample.record_proposal_info and chunks[0].logqs is not None:
                prop_kw = dict(
                    ms=np.concatenate([c.ms for c in chunks])[valid_mask],
                    thetas=np.concatenate(
                        [c.thetas for c in chunks])[valid_mask],
                    proposal_pds=np.exp(np.concatenate(
                        [c.logqs for c in chunks]))[valid_mask],
                )
            else:
                prop_kw = {}
            sample.set_all_records(
                sumstats=np.concatenate([c.sumstats for c in chunks])[valid_mask],
                distances=np.concatenate([c.distances for c in chunks])[valid_mask],
                accepted=acc_mask[valid_mask],
                **prop_kw,
            )
        return sample
