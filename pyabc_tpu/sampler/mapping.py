"""Map-style and executor-based samplers.

Reference parity: ``pyabc/sampler/mapping.py::MappingSampler`` and
``pyabc/sampler/concurrent_future.py::ConcurrentFutureSampler`` (+
``pyabc/sampler/eps_sampling_function.py::sample_until_n_accepted_proto``).
Static batch scheduling over any user-supplied map / Executor — the
pluggable escape hatch for ipyparallel / MPI pools / dask executors.
"""
from __future__ import annotations

import numpy as np

from .base import HostRecords, Sample, Sampler, particle_record


def _batch_worker(simulate_one, seed, chunk):
    np.random.seed(seed)
    results = []
    for _ in range(chunk):
        results.append(simulate_one())
    return results


class MappingSampler(Sampler):
    """Static oversubmitted batches through a map function (reference
    MappingSampler). ``map_=`` accepts builtin map, ipyparallel view.map,
    dask client.map-like callables."""

    def __init__(self, map_=map, mapper_pickles: bool = False,
                 chunk_size: int = 1, batch_factor: float = 2.0):
        super().__init__()
        self.map_ = map_
        self.mapper_pickles = mapper_pickles
        self.chunk_size = int(chunk_size)
        self.batch_factor = float(batch_factor)

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        sample = self.sample_factory()
        accepted = []
        ids = []
        all_records = []
        n_eval = 0
        rate_guess = 0.5
        while len(accepted) < n:
            needed = n - len(accepted)
            n_jobs = max(int(needed / rate_guess * self.batch_factor), 1)
            n_chunks = max(n_jobs // self.chunk_size, 1)
            seeds = np.random.randint(0, 2**31 - 1, size=n_chunks)
            from functools import partial

            results = self.map_(
                partial(_batch_worker, simulate_one),
                [int(s) for s in seeds],
                [self.chunk_size] * n_chunks,
            )
            for chunk in results:
                for particle in chunk:
                    slot = n_eval
                    n_eval += 1
                    if sample.record_rejected:
                        all_records.append(particle_record(particle))
                    if particle.accepted or all_accepted:
                        accepted.append(particle)
                        ids.append(slot)
            rate_guess = max(len(accepted) / max(n_eval, 1), 1.0 / max(n_eval, 1))
        self.nr_evaluations_ = n_eval
        order = np.argsort(ids, kind="stable")[:n]
        sample.accepted_particles = [accepted[i] for i in order]
        sample.accepted_proposal_ids = np.asarray(ids)[order]
        if sample.record_rejected and all_records:
            sample.host_all_records = HostRecords.from_tuples(all_records)
        return sample


class ConcurrentFutureSampler(Sampler):
    """Static batches over any ``concurrent.futures.Executor`` (reference
    ConcurrentFutureSampler): ThreadPool, ProcessPool, or Dask's
    ``client.get_executor()``."""

    def __init__(self, cfuture_executor, client_max_jobs: int = 200,
                 batch_size: int = 1):
        super().__init__()
        self.executor = cfuture_executor
        self.client_max_jobs = int(client_max_jobs)
        self.batch_size = int(batch_size)

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        import concurrent.futures as cf

        sample = self.sample_factory()
        accepted, ids, all_records = [], [], []
        n_eval = 0
        pending = set()
        next_seed = np.random.randint(0, 2**30)
        while len(accepted) < n or pending:
            while (len(pending) < self.client_max_jobs
                   and len(accepted) < n):
                pending.add(self.executor.submit(
                    _batch_worker, simulate_one, next_seed, self.batch_size
                ))
                next_seed += 1
            done, pending = cf.wait(pending, return_when=cf.FIRST_COMPLETED)
            for fut in done:
                for particle in fut.result():
                    slot = n_eval
                    n_eval += 1
                    if sample.record_rejected:
                        all_records.append(particle_record(particle))
                    if particle.accepted or all_accepted:
                        accepted.append(particle)
                        ids.append(slot)
            if len(accepted) >= n:
                for fut in pending:
                    fut.cancel()
                pending = {f for f in pending if not f.cancel()}
                for fut in pending:
                    fut.result()
                pending = set()
        self.nr_evaluations_ = n_eval
        order = np.argsort(ids, kind="stable")[:n]
        sample.accepted_particles = [accepted[i] for i in order]
        sample.accepted_proposal_ids = np.asarray(ids)[order]
        if sample.record_rejected and all_records:
            sample.host_all_records = HostRecords.from_tuples(all_records)
        return sample
