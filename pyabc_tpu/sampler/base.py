"""Sampler base & Sample containers.

Reference parity: ``pyabc/sampler/base.py::{Sampler, Sample, SampleFactory}``.
The reference contract is ``sample_until_n_accepted(n, simulate_one, t, ...)
-> Sample`` where simulate_one is a pickled scalar closure; the TPU-native
contract passes a `GenerationContext` (see ``pyabc_tpu.inference.util``)
which carries BOTH the scalar host closure (reference semantics, oracle
path) and the batched jit-compiled round kernel (device path). Samplers
declare which they consume.

`Sample` is struct-of-arrays: the accepted particles as dense arrays plus
(optionally) all evaluated records for adaptive components
(``record_rejected``, set via ``configure_sampler`` by e.g.
AdaptivePNormDistance — same coupling as the reference).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np


def exp_normalize_log_weights(log_w) -> np.ndarray:
    """Stable exp of relative log importance weights (float64).

    -inf entries get weight 0; an all-non-finite input degrades to uniform
    weights (an all-accepted calibration round). Shared by the fused-sampler
    finalization and the multi-generation chunk loop.
    """
    log_w = np.asarray(log_w, np.float64)
    finite = np.isfinite(log_w)
    if finite.any():
        mx = log_w[finite].max()
        return np.where(finite, np.exp(log_w - mx), 0.0)
    return np.ones_like(log_w)


class DeviceRecords:
    """All-evaluations record ring kept ON DEVICE (lazy fetch).

    The fused generation kernel's record ring is ~100s of KB; over a TPU
    tunnel fetching it dominates the generation wall time, while its only
    consumer (adaptive distance reweighting) is a per-column reduction that
    the device does in microseconds. Components that understand devices
    reduce in place (``pyabc_tpu.distance.scale.device_scale_fn``); anything
    else triggers a one-time host fetch via :meth:`to_host` (also wired to
    ``np.asarray``).
    """

    def __init__(self, sumstats_dev, valid_dev, scale=None,
                 sync_ledger=None):
        from ..observability import NULL_SYNC_LEDGER

        self.sumstats_dev = sumstats_dev
        self.valid_dev = valid_dev
        #: (S,) scale vector precomputed by the in-kernel reduction, if the
        #: active distance registered one (Distance.device_record_reduce)
        self.scale = scale
        #: the owning run's SyncLedger: the lazy fetches below are blocking
        #: round trips and must count into syncs_per_run (SYNC001)
        self.sync_ledger = (sync_ledger if sync_ledger is not None
                            else NULL_SYNC_LEDGER)
        self._host: np.ndarray | None = None

    def to_host(self) -> np.ndarray:
        """Fetch and mask: (n_valid, S) float64 matrix."""
        if self._host is None:
            import jax

            ss, valid = jax.device_get((self.sumstats_dev, self.valid_dev))
            self.sync_ledger.record("records_fetch",
                                    getattr(ss, "nbytes", 0))
            self._host = np.asarray(ss, np.float64)[np.asarray(valid, bool)]
        return self._host

    def __array__(self, dtype=None, copy=None):
        host = self.to_host()
        return host.astype(dtype) if dtype is not None else host

    @property
    def shape(self):
        return self.to_host().shape


@dataclass
class HostRecords:
    """All-evaluations records from the host (scalar-closure) samplers.

    Mirrors the reference's rejected-particle record: summary statistics,
    distance and acceptance per evaluation, plus the proposal identity
    (m, parameter) and the proposal density the particle was drawn under
    (``proposal_pds`` = reference ``transition_pd_prev``) so the
    AcceptanceRateScheme can importance-reweight the record to the NEXT
    generation's proposal.
    """

    sum_stats: list
    distances: np.ndarray
    accepted: np.ndarray
    ms: np.ndarray | None = None
    parameters: list | None = None
    proposal_pds: np.ndarray | None = None

    @classmethod
    def from_particles(cls, particles) -> "HostRecords":
        return cls(
            sum_stats=[p.sum_stat for p in particles],
            distances=np.asarray([p.distance for p in particles]),
            accepted=np.asarray([p.accepted for p in particles], bool),
            ms=np.asarray([p.m for p in particles], np.int32),
            parameters=[p.parameter for p in particles],
            proposal_pds=np.asarray(
                [p.proposal_pd for p in particles], np.float64
            ),
        )

    @classmethod
    def from_tuples(cls, records) -> "HostRecords":
        """From (sum_stat, distance, accepted, m, parameter, proposal_pd)
        tuples (the queue-friendly form the multiprocess workers ship)."""
        return cls(
            sum_stats=[r[0] for r in records],
            distances=np.asarray([r[1] for r in records]),
            accepted=np.asarray([r[2] for r in records], bool),
            ms=np.asarray([r[3] for r in records], np.int32),
            parameters=[r[4] for r in records],
            proposal_pds=np.asarray([r[5] for r in records], np.float64),
        )


def particle_record(p) -> tuple:
    """The picklable per-evaluation record tuple for HostRecords.from_tuples."""
    return (p.sum_stat, p.distance, p.accepted, p.m, p.parameter,
            p.proposal_pd)


class Sample:
    """One generation's harvest (pyabc Sample), struct-of-arrays.

    ``proposal_ids`` are global eval-slot indices assigned in proposal order;
    sorting by them and trimming overshoot beyond n keeps any dynamic /
    batched sampler statistically equivalent to sequential sampling — the
    reference's unbiasedness invariant (SURVEY.md §3.4, §5.2).
    """

    def __init__(self, record_rejected: bool = False,
                 max_nr_rejected: int = np.inf,
                 record_proposal_info: bool = False):
        self.record_rejected = record_rejected
        self.max_nr_rejected = max_nr_rejected
        self.record_proposal_info = record_proposal_info
        self.is_look_ahead: bool = False
        # accepted particle arrays
        self.ms: np.ndarray | None = None
        self.thetas: np.ndarray | None = None
        self.weights: np.ndarray | None = None
        self.distances: np.ndarray | None = None
        self.sumstats: np.ndarray | None = None
        self.proposal_ids: np.ndarray | None = None
        # all evaluated records (accepted + rejected), for adaptive components
        self.all_sumstats: np.ndarray | None = None
        self.all_distances: np.ndarray | None = None
        self.all_accepted: np.ndarray | None = None
        # proposal identity + density of every record (device samplers;
        # host samplers carry the same via HostRecords) — feeds the
        # AcceptanceRateScheme record reweighting
        self.all_ms: np.ndarray | None = None
        self.all_thetas: np.ndarray | None = None
        self.all_proposal_pds: np.ndarray | None = None
        #: on-device record ring (fused sampler): lazily fetched alternative
        #: to ``all_sumstats``
        self.device_records: DeviceRecords | None = None

    @property
    def n_accepted(self) -> int:
        return 0 if self.ms is None else len(self.ms)

    def set_accepted(self, *, ms, thetas, weights, distances, sumstats,
                     proposal_ids) -> None:
        order = np.argsort(proposal_ids, kind="stable")
        self.ms = np.asarray(ms)[order]
        self.thetas = np.asarray(thetas)[order]
        self.weights = np.asarray(weights)[order]
        self.distances = np.asarray(distances)[order]
        # None: the fetch skipped sum stats (History.store_sum_stats off)
        self.sumstats = (
            np.asarray(sumstats)[order] if sumstats is not None else None
        )
        self.proposal_ids = np.asarray(proposal_ids)[order]

    def trim(self, n: int) -> None:
        """Deterministic overshoot trim: keep the first n by eval-slot id."""
        if self.n_accepted <= n:
            return
        for name in ("ms", "thetas", "weights", "distances", "sumstats",
                     "proposal_ids"):
            v = getattr(self, name)
            if v is not None:
                setattr(self, name, v[:n])

    def set_all_records(self, *, sumstats, distances, accepted,
                        ms=None, thetas=None, proposal_pds=None) -> None:
        """Store the all-evaluations record, applying the finite
        ``max_nr_rejected`` retention (accepted-first) to EVERY array so
        the optional proposal-info columns stay row-aligned with the
        distances."""
        if not self.record_rejected:
            return
        k = len(sumstats)
        if np.isfinite(self.max_nr_rejected) and k > self.max_nr_rejected:
            keep = np.concatenate([
                np.flatnonzero(accepted),
                np.flatnonzero(~np.asarray(accepted))[: int(self.max_nr_rejected)],
            ])
            sumstats, distances, accepted = (
                sumstats[keep], distances[keep], accepted[keep]
            )
            if ms is not None:
                ms, thetas, proposal_pds = (
                    ms[keep], thetas[keep], proposal_pds[keep]
                )
        self.all_sumstats = np.asarray(sumstats)
        self.all_distances = np.asarray(distances)
        self.all_accepted = np.asarray(accepted)
        if ms is not None:
            self.all_ms = np.asarray(ms)
            self.all_thetas = np.asarray(thetas)
            self.all_proposal_pds = np.asarray(proposal_pds)

    def get_all_sum_stats(self) -> np.ndarray:
        """All recorded sum stats (accepted + rejected if recorded)."""
        if self.all_sumstats is not None:
            return self.all_sumstats
        if self.device_records is not None:
            return self.device_records.to_host()
        return self.sumstats


@dataclass
class SampleFactory:
    """Carries sampler-wide sample options (pyabc SampleFactory).

    Adaptive components flip ``record_rejected`` in ``configure_sampler``;
    Temperature additionally flips ``record_proposal_info`` so records
    carry (m, theta, proposal density) for the AcceptanceRateScheme's
    reweighting.
    """

    record_rejected: bool = False
    max_nr_rejected: int = np.inf
    record_proposal_info: bool = False

    def __call__(self) -> Sample:
        return Sample(self.record_rejected, self.max_nr_rejected,
                      self.record_proposal_info)


class Sampler:
    """Abstract sampler (pyabc Sampler).

    ``nr_evaluations_`` reports total forward simulations of the last call.
    """

    def __init__(self):
        from ..observability import (
            NULL_METRICS,
            NULL_SYNC_LEDGER,
            NULL_TRACER,
        )

        self.nr_evaluations_: int = 0
        self.sample_factory = SampleFactory()
        self.show_progress = False
        self.analysis_id: str | None = None
        #: observability sinks (pyabc_tpu/observability/): ABCSMC rebinds
        #: these to the run's tracer/registry at run() time; the no-op
        #: defaults keep standalone sampler use free of overhead
        self.tracer = NULL_TRACER
        self.metrics = NULL_METRICS
        #: device-sync accounting: device-backed samplers record every
        #: blocking host<->device round trip here (ABCSMC rebinds this to
        #: the run's ledger, feeding the bench's tunnel-floor attribution)
        self.sync_ledger = NULL_SYNC_LEDGER

    def set_analysis_id(self, analysis_id: str):
        self.analysis_id = analysis_id

    def sample_until_n_accepted(self, n: int, simulate_one, t: int, *,
                                max_eval: float = np.inf,
                                all_accepted: bool = False,
                                ana_vars=None) -> Sample:
        raise NotImplementedError

    def stop(self) -> None:
        """Release resources (reference: redis/dask teardown)."""

    def __repr__(self):
        return f"{type(self).__name__}()"
