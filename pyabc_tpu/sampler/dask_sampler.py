"""Dask.distributed sampler (gated on the optional ``distributed`` package).

Reference parity: ``pyabc/sampler/dask_sampler.py::DaskDistributedSampler``
— multi-node static/batched sampling with oversubmission (``batch_size``,
``client_max_jobs``) over a ``dask.distributed.Client``, polling completed
futures dynamically.

TPU-first note: on gang-scheduled TPU slices the mesh/ICI path
(``BatchedSampler`` + ``mesh=``, SURVEY.md §5.8) replaces broker-based
scaling entirely; this sampler exists for the reference's CPU-cluster
use-case (farming out non-JAX host simulators) and activates only when
``distributed`` is installed.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import Sampler
from .mapping import ConcurrentFutureSampler

logger = logging.getLogger("ABC.Sampler")


def _require_distributed():
    try:
        import distributed  # noqa: F401

        return distributed
    except ImportError as err:  # pragma: no cover - exercised when absent
        raise ImportError(
            "DaskDistributedSampler needs the optional 'distributed' "
            "package (pip install distributed). On TPU slices prefer the "
            "default BatchedSampler with mesh= for scale-out; for local "
            "multiprocessing use MulticoreEvalParallelSampler."
        ) from err


class DaskDistributedSampler(Sampler):
    """Evaluation batches over a Dask cluster (reference
    DaskDistributedSampler).

    Parameters mirror the reference: ``dask_client`` (default: a fresh
    local ``Client()``), ``client_max_jobs`` concurrent futures,
    ``batch_size`` evaluations per future.
    """

    def __init__(self, dask_client=None, client_max_jobs: int = 200,
                 batch_size: int = 1):
        super().__init__()
        distributed = _require_distributed()
        if dask_client is None:  # pragma: no cover - needs a live cluster
            dask_client = distributed.Client()
        self.client = dask_client
        self.client_max_jobs = int(client_max_jobs)
        self.batch_size = int(batch_size)
        # delegate the scheduling loop: dask's Executor interface gives the
        # same completed-future polling the reference implements by hand
        self._inner = ConcurrentFutureSampler(
            self.client.get_executor(),
            client_max_jobs=self.client_max_jobs,
            batch_size=self.batch_size,
        )
        self._inner.sample_factory = self.sample_factory

    def sample_until_n_accepted(self, n, simulate_one, t, *,
                                max_eval=np.inf, all_accepted=False,
                                ana_vars=None):
        self._inner.sample_factory = self.sample_factory
        sample = self._inner.sample_until_n_accepted(
            n, simulate_one, t, max_eval=max_eval,
            all_accepted=all_accepted, ana_vars=ana_vars,
        )
        self.nr_evaluations_ = self._inner.nr_evaluations_
        return sample

    def stop(self) -> None:  # pragma: no cover - needs a live cluster
        try:
            self.client.close()
        except Exception:
            logger.info("dask client close failed (already down?)",
                        exc_info=True)
