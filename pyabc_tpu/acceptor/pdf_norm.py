"""pdf normalization strategies for the stochastic acceptor.

Reference parity: ``pyabc/acceptor/pdf_norm.py::{pdf_norm_from_kernel,
pdf_norm_max_found, ScaledPDFNorm}``. All values are on log scale.
"""
from __future__ import annotations

import numpy as np


def pdf_norm_from_kernel(kernel_val=None, pdf_max=None, max_found=None,
                         prev_pdf_norm=None) -> float:
    """Use the kernel's analytic maximum density (requires pdf_max)."""
    if pdf_max is None:
        raise ValueError("kernel provides no analytic pdf_max")
    return float(pdf_max)

def pdf_norm_max_found(kernel_val=None, pdf_max=None, max_found=None,
                       prev_pdf_norm=None) -> float:
    """Normalize by the maximum kernel value found so far (reference default).

    Uses the analytic maximum when available and finite, otherwise the
    running max over all evaluated kernel values (never decreasing).
    """
    candidates = []
    if pdf_max is not None and np.isfinite(pdf_max):
        candidates.append(float(pdf_max))
    if max_found is not None and np.isfinite(max_found):
        candidates.append(float(max_found))
    if prev_pdf_norm is not None and np.isfinite(prev_pdf_norm):
        candidates.append(float(prev_pdf_norm))
    if not candidates:
        return 0.0
    # analytic max dominates if present; otherwise monotone running max
    if pdf_max is not None and np.isfinite(pdf_max):
        return float(pdf_max)
    return float(max(candidates))


class ScaledPDFNorm:
    """Down-scale the norm when acceptance would be pathologically rare
    (pyabc ScaledPDFNorm): uses max_found minus an offset once the plain
    max-found norm would imply acceptance rates below ``target``.
    """

    def __init__(self, factor: float = 10.0, alpha: float = 0.5):
        self.factor = float(factor)
        self.alpha = float(alpha)

    def __call__(self, kernel_val=None, pdf_max=None, max_found=None,
                 prev_pdf_norm=None) -> float:
        base = pdf_norm_max_found(
            kernel_val=kernel_val, pdf_max=pdf_max, max_found=max_found,
            prev_pdf_norm=prev_pdf_norm,
        )
        if kernel_val is None or len(np.atleast_1d(kernel_val)) == 0:
            return base
        vals = np.asarray(kernel_val, np.float64)
        quant = np.quantile(vals, self.alpha)
        offsetted = quant + np.log(self.factor)
        return float(min(base, offsetted)) if offsetted < base else float(base)

    @property
    def __name__(self):
        return "ScaledPDFNorm"
