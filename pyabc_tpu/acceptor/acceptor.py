"""Acceptors — decide particle acceptance given distance and epsilon.

Reference parity: ``pyabc/acceptor/acceptor.py::{AcceptorResult, Acceptor,
UniformAcceptor, SimpleFunctionAcceptor, StochasticAcceptor}``.

`StochasticAcceptor` implements noisy ABC: with a stochastic kernel distance
returning log density v = log p(x_0 | x), accept with probability
exp((v - pdf_norm)/T); over-unity densities (v > pdf_norm) are accepted with
an importance weight exp((v - pdf_norm)/T) > 1 (exact correction, reference
semantics). The device form keeps everything in log space inside the kernel.
"""
from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..distance.kernel import SCALE_LIN, SCALE_LOG, StochasticKernel
from .pdf_norm import pdf_norm_max_found


class AcceptorResult:
    """(distance, accept, weight) triple (pyabc AcceptorResult)."""

    def __init__(self, distance: float, accept: bool, weight: float = 1.0):
        self.distance = distance
        self.accept = accept
        self.weight = weight

    def __iter__(self):
        yield self.distance
        yield self.accept
        yield self.weight

    def __repr__(self):
        return (f"AcceptorResult(distance={self.distance}, "
                f"accept={self.accept}, weight={self.weight})")


class Acceptor:
    """Abstract acceptor (pyabc Acceptor)."""

    def initialize(self, t: int, get_weighted_distances: Callable | None = None,
                   distance_function=None, x_0=None) -> None:
        pass

    def update(self, t: int, get_weighted_distances: Callable | None = None,
               prev_temp: float | None = None,
               acceptance_rate: float | None = None) -> None:
        pass

    def __call__(self, distance_function, eps, x, x_0, t, par) -> AcceptorResult:
        raise NotImplementedError

    def requires_calibration(self) -> bool:
        return False

    def is_adaptive(self) -> bool:
        return False

    def get_epsilon_config(self, t: int) -> dict:
        """Info for the epsilon schedule (used by Temperature)."""
        return {}

    def get_config(self) -> dict:
        return {"name": type(self).__name__}

    def __repr__(self):
        return f"{type(self).__name__}()"

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return False

    def device_params(self, t: int | None = None):
        return ()

    def device_fn(self, distance_device_fn):
        """Traceable ``fn(key, x, x0, eps, dist_params, acc_params) ->
        (distance, accept_bool, log_acc_weight)``."""
        raise NotImplementedError


class UniformAcceptor(Acceptor):
    """Accept iff distance <= epsilon (pyabc UniformAcceptor).

    ``use_complete_history``: accept only if the distance also satisfies all
    previous epsilon thresholds (relevant when the distance function changed
    between generations).
    """

    def __init__(self, use_complete_history: bool = False):
        self.use_complete_history = bool(use_complete_history)
        self._eps_history: dict[int, float] = {}

    def note_epsilon(self, t: int, eps_value: float,
                     distance_changed: bool) -> None:
        """Orchestrator hook: record the threshold used at generation t.

        When the distance function changed, thresholds recorded under the
        previous weighting are incomparable to new distance values — the
        trail restarts (both paths share this rule).
        """
        if distance_changed:
            self._eps_history.clear()
        self._eps_history[t] = float(eps_value)

    def _historic_min(self, t: int | None) -> float:
        vals = [e for s, e in self._eps_history.items()
                if t is None or s < t]
        return min(vals) if vals else np.inf

    def __call__(self, distance_function, eps, x, x_0, t, par) -> AcceptorResult:
        d = distance_function(x, x_0, t, par)
        accept = d <= eps(t)
        if accept and self.use_complete_history:
            accept = d <= self._historic_min(t)
        return AcceptorResult(distance=d, accept=bool(accept))

    def is_device_compatible(self) -> bool:
        return True

    def device_params(self, t=None):
        if not self.use_complete_history:
            return ()
        return jnp.asarray(self._historic_min(t), jnp.float32)

    def device_fn(self, distance_device_fn):
        use_hist = self.use_complete_history

        def fn(key, x, x0, eps, dist_params, acc_params):
            d = distance_device_fn(x, x0, dist_params)
            accept = d <= eps
            if use_hist:
                accept = accept & (d <= acc_params)
            return d, accept, jnp.zeros(())  # log weight 0 => weight 1

        return fn


class SimpleFunctionAcceptor(Acceptor):
    """Adapter for a plain callable (pyabc SimpleFunctionAcceptor)."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def __call__(self, distance_function, eps, x, x_0, t, par) -> AcceptorResult:
        out = self.fn(distance_function, eps, x, x_0, t, par)
        if isinstance(out, AcceptorResult):
            return out
        if isinstance(out, tuple):
            return AcceptorResult(*out)
        raise TypeError(f"acceptor function returned {out!r}")

    @staticmethod
    def assert_acceptor(maybe_acceptor) -> "Acceptor":
        if isinstance(maybe_acceptor, Acceptor):
            return maybe_acceptor
        if callable(maybe_acceptor):
            return SimpleFunctionAcceptor(maybe_acceptor)
        raise TypeError(f"cannot coerce {maybe_acceptor!r} into an Acceptor")


class StochasticAcceptor(Acceptor):
    """Exact-likelihood stochastic acceptor (pyabc StochasticAcceptor).

    Requires the distance to be a `StochasticKernel` and the epsilon schedule
    to be a `Temperature`. At temperature T, a particle with kernel value v
    (log scale) is accepted with probability min(1, exp((v - pdf_norm)/T));
    if exp((v - pdf_norm)/T) > 1 the particle is accepted with that value as
    importance weight.
    """

    def __init__(self, pdf_norm_method: Callable = pdf_norm_max_found,
                 apply_importance_weighting: bool = True,
                 log_file: str | None = None):
        self.pdf_norm_method = pdf_norm_method
        self.apply_importance_weighting = bool(apply_importance_weighting)
        self.log_file = log_file
        #: per-generation normalization constants (log scale)
        self.pdf_norms: dict[int, float] = {}
        self._kernel: StochasticKernel | None = None
        self._max_found: float = -np.inf

    def requires_calibration(self) -> bool:
        return True

    def is_adaptive(self) -> bool:
        return True

    def initialize(self, t, get_weighted_distances=None, distance_function=None,
                   x_0=None):
        if not isinstance(distance_function, StochasticKernel):
            raise TypeError(
                "StochasticAcceptor requires a StochasticKernel distance"
            )
        self._kernel = distance_function
        self._update_norm(t, get_weighted_distances)

    def update(self, t, get_weighted_distances=None, prev_temp=None,
               acceptance_rate=None):
        self._update_norm(t, get_weighted_distances)

    def _update_norm(self, t, get_weighted_distances):
        kernel_value = None
        if get_weighted_distances is not None:
            df = get_weighted_distances()
            vals = np.asarray(df["distance"], np.float64)
            if self._kernel.ret_scale == SCALE_LIN:
                vals = np.log(np.maximum(vals, 1e-300))
            if len(vals):
                self._max_found = max(self._max_found, float(np.max(vals)))
                kernel_value = vals
        pdf_max = self._kernel.pdf_max if self._kernel else None
        if pdf_max is not None and self._kernel.ret_scale == SCALE_LIN:
            pdf_max = np.log(max(pdf_max, 1e-300))
        norm = self.pdf_norm_method(
            kernel_val=kernel_value,
            pdf_max=pdf_max,
            max_found=self._max_found,
            prev_pdf_norm=(
                max(self.pdf_norms.values()) if self.pdf_norms else None
            ),
        )
        self.pdf_norms[t] = float(norm)
        if self.log_file:
            import json

            try:
                with open(self.log_file) as fh:
                    log = json.load(fh)
            except (OSError, ValueError):
                log = {}
            log[str(t)] = self.pdf_norms[t]
            with open(self.log_file, "w") as fh:
                json.dump(log, fh, indent=1)

    def get_epsilon_config(self, t: int) -> dict:
        return {
            "pdf_norm": self.pdf_norms.get(t),
            "kernel_scale": self._kernel.ret_scale if self._kernel else SCALE_LOG,
        }

    def __call__(self, distance_function, eps, x, x_0, t, par) -> AcceptorResult:
        v = distance_function(x, x_0, t, par)
        logv = (
            float(np.log(max(v, 1e-300)))
            if distance_function.ret_scale == SCALE_LIN
            else float(v)
        )
        pdf_norm = self.pdf_norms[t]
        temp = eps(t)
        log_ratio = (logv - pdf_norm) / temp
        if log_ratio >= 0:
            accept = True
            weight = float(np.exp(log_ratio)) if self.apply_importance_weighting else 1.0
        else:
            accept = bool(np.random.uniform() < np.exp(log_ratio))
            weight = 1.0
        return AcceptorResult(distance=v, accept=accept, weight=weight)

    def delayed_accept_fn(self, t: int, temperature: float):
        """Host-side delayed stochastic acceptance for adopted look-ahead
        generations (fixed-schedule configs — ListTemperature +
        ``pdf_norm_from_kernel`` — where nothing in the acceptance rule
        depends on the adopted generation's own records).

        A preliminary worker only simulated: its particle carries the
        kernel value as ``distance`` (generation-invariant: stochastic
        kernels never re-weight between generations) and the
        prior/proposal importance ratio as ``weight``. This applies the
        SAME rule as :meth:`__call__` — accept with probability
        ``min(1, exp((v - pdf_norm)/T))``, folding the above-norm excess
        into the importance weight — so the adopted generation is
        distributed exactly as a serially-sampled one."""
        pdf_norm = self.pdf_norms[t]
        lin = self._kernel is not None and self._kernel.ret_scale == SCALE_LIN
        apply_iw = self.apply_importance_weighting
        temp = float(temperature)

        def accept(p) -> bool:
            logv = (
                float(np.log(max(p.distance, 1e-300))) if lin
                else float(p.distance)
            )
            log_ratio = (logv - pdf_norm) / temp
            if log_ratio >= 0:
                if apply_iw:
                    p.weight *= float(np.exp(log_ratio))
                return True
            return bool(np.random.uniform() < np.exp(log_ratio))

        return accept

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return self._kernel is not None and self._kernel.is_device_compatible()

    def device_params(self, t=None):
        # .get with 0.0: during calibration the prior kernel runs at
        # eps=+inf BEFORE initialize() populates pdf_norms — the log-ratio
        # (v - pdf_norm)/inf is 0 regardless, so any finite norm is inert
        return jnp.asarray(self.pdf_norms.get(t, 0.0), jnp.float32)

    def device_fn(self, distance_device_fn):
        lin = self._kernel is not None and self._kernel.ret_scale == SCALE_LIN
        apply_iw = self.apply_importance_weighting

        def fn(key, x, x0, temp, dist_params, pdf_norm):
            import jax

            v = distance_device_fn(x, x0, dist_params)
            logv = jnp.log(jnp.maximum(v, 1e-30)) if lin else v
            log_ratio = (logv - pdf_norm) / temp
            u = jax.random.uniform(key)
            accept = jnp.log(u) < log_ratio
            log_w = jnp.where(
                (log_ratio > 0) & apply_iw, log_ratio, 0.0
            )
            return v, accept, log_w

        return fn

    def get_config(self):
        return {"name": type(self).__name__,
                "pdf_norm_method": getattr(self.pdf_norm_method, "__name__", "?")}
