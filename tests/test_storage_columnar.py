"""Columnar generation-batch History (round 17): the hybrid store's
contracts.

1. BIT-IDENTITY — the same generation appended as a packed-fetch
   GenerationBatch (columnar) and as a Population (row store) reads
   back bit-identical through EVERY History query path: distributions,
   weights, weighted distances, weighted sum stats, parameter names,
   particle counts.
2. DTYPE PRESERVATION — narrow fetch dtypes (float16) survive to disk
   instead of widening to REAL; float64 reads are exact upcasts.
3. DURABILITY — prune_from deletes generation files with their
   metadata rows; the async-writer flush ordering (db-at-or-ahead
   before a checkpoint rename) holds because the Parquet file lands
   before the metadata commit inside the same append.
4. GATING — without pyarrow the columnar store fails at construction
   with an informative error naming the package AND the working
   default; the row store never imports pyarrow (the
   ``bytes_storage._has_parquet`` contract, proven process-wide by the
   PYABC_TPU_BLOCK_PYARROW CI leg).
5. END-TO-END — a fused ABCSMC run on a ``sqlite+columnar:///`` url
   produces a posterior and epsilon trail bit-identical to the same
   seed on the row store, and resumes via History load().
"""
import os

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.core.parameters import ParameterSpace
from pyabc_tpu.core.population import Population
from pyabc_tpu.core.sumstat_spec import SumStatSpec
from pyabc_tpu.sampler.base import Sample, exp_normalize_log_weights
from pyabc_tpu.storage import GenerationBatch, History
from pyabc_tpu.storage.columnar import has_pyarrow

needs_pyarrow = pytest.mark.skipif(
    not has_pyarrow(), reason="columnar store needs the optional pyarrow")

N, D, S = 120, 2, 3
MODEL_NAMES = ["m0", "m1"]
PARAM_NAMES = [["a", "b"], ["b", "a"]]


def _fetch_arrays(seed: int):
    """A synthetic packed-fetch generation: narrow dtypes, slot order
    scrambled (the batch must re-sort exactly like Sample.set_accepted)."""
    r = np.random.default_rng(seed)
    return {
        "ms": r.integers(0, 2, N).astype(np.int32),
        "thetas": r.normal(size=(N, D)).astype(np.float16),
        "log_weights": r.normal(size=N).astype(np.float16),
        "distances": np.abs(r.normal(size=N)).astype(np.float16),
        "sumstats": r.normal(size=(N, S)).astype(np.float16),
        "slots": r.permutation(N),
    }


def _as_population(arrs) -> Population:
    """The row-store reference path: exactly what the fused loop's
    deferred ``_build`` does with the same fetch arrays."""
    sample = Sample()
    sample.set_accepted(
        ms=arrs["ms"],
        thetas=np.asarray(arrs["thetas"], np.float64),
        weights=exp_normalize_log_weights(arrs["log_weights"]),
        distances=np.asarray(arrs["distances"], np.float64),
        sumstats=np.asarray(arrs["sumstats"], np.float64),
        proposal_ids=arrs["slots"],
    )
    return Population(
        ms=sample.ms, thetas=sample.thetas, weights=sample.weights,
        distances=sample.distances, sumstats=sample.sumstats,
        spaces=[ParameterSpace(n) for n in PARAM_NAMES],
        sumstat_spec=SumStatSpec({"x": np.zeros(S)}),
        model_names=MODEL_NAMES,
    )


def _as_batch(arrs) -> GenerationBatch:
    return GenerationBatch.from_fetch(
        ms=arrs["ms"], thetas=arrs["thetas"],
        log_weights=arrs["log_weights"], distances=arrs["distances"],
        sumstats=arrs["sumstats"], slots=arrs["slots"],
        param_names=PARAM_NAMES,
    )


def _open_pair(tmp_path, gens=3):
    """(row History, columnar History) holding the same generations."""
    hr = History(f"sqlite:///{tmp_path}/rows.db")
    hc = History(f"sqlite+columnar:///{tmp_path}/col.db")
    for h in (hr, hc):
        h.store_initial_data(None, {}, {"x": np.zeros(S)}, {"a": 1.0},
                             MODEL_NAMES, "{}", "{}", "{}")
    for t in range(gens):
        arrs = _fetch_arrays(seed=100 + t)
        hr.append_population(t, 1.0 - 0.1 * t, _as_population(arrs),
                             3 * N, MODEL_NAMES)
        hc.append_population(t, 1.0 - 0.1 * t, _as_batch(arrs),
                             3 * N, MODEL_NAMES)
    return hr, hc


# ================================================= bit-identity contract
@needs_pyarrow
def test_columnar_reads_bit_identical_to_row_store(tmp_path):
    hr, hc = _open_pair(tmp_path)
    for t in range(3):
        for m in (0, 1):
            df_r, w_r = hr.get_distribution(m, t)
            df_c, w_c = hc.get_distribution(m, t)
            # same columns (alphabetical, like the SQL pivot), same
            # rows in the same order, same exact float values
            assert list(df_r.columns) == list(df_c.columns)
            assert np.array_equal(df_r.to_numpy(), df_c.to_numpy())
            assert np.array_equal(w_r, w_c)
            assert (hr.get_parameter_names(m, t)
                    == hc.get_parameter_names(m, t))
        wd_r, wd_c = hr.get_weighted_distances(t), hc.get_weighted_distances(t)
        assert np.array_equal(wd_r["distance"].to_numpy(),
                              wd_c["distance"].to_numpy())
        assert np.array_equal(wd_r["w"].to_numpy(), wd_c["w"].to_numpy())
        ws_r, st_r = hr.get_weighted_sum_stats(t)
        ws_c, st_c = hc.get_weighted_sum_stats(t)
        assert np.array_equal(ws_r, ws_c)
        assert np.array_equal(st_r, st_c)
        assert st_c.dtype == np.float64
    assert hr.get_nr_particles_per_population().equals(
        hc.get_nr_particles_per_population())
    ext_r, ext_c = hr.get_population_extended(1), hc.get_population_extended(1)
    assert len(ext_r) == len(ext_c) == N * D
    assert sorted(ext_r["par_value"]) == sorted(ext_c["par_value"])


@needs_pyarrow
def test_population_append_equals_batch_append_on_columnar(tmp_path):
    """The two columnar ingest doors (host-path Population, packed-fetch
    GenerationBatch) store identical bytes-on-read."""
    h1 = History(f"sqlite+columnar:///{tmp_path}/a.db")
    h2 = History(f"sqlite+columnar:///{tmp_path}/b.db")
    for h in (h1, h2):
        h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                             MODEL_NAMES, "{}", "{}", "{}")
    arrs = _fetch_arrays(seed=5)
    h1.append_population(0, 1.0, _as_population(arrs), 3 * N, MODEL_NAMES)
    h2.append_population(0, 1.0, _as_batch(arrs), 3 * N, MODEL_NAMES)
    for m in (0, 1):
        df1, w1 = h1.get_distribution(m, 0)
        df2, w2 = h2.get_distribution(m, 0)
        assert np.array_equal(df1.to_numpy(), df2.to_numpy())
        assert np.array_equal(w1, w2)


# ============================================== dtype / layout contracts
@needs_pyarrow
def test_narrow_dtypes_preserved_on_disk(tmp_path):
    import pyarrow.parquet as pq

    h = History(f"sqlite+columnar:///{tmp_path}/n.db")
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    h.append_population(0, 1.0, _as_batch(_fetch_arrays(1)),
                        3 * N, MODEL_NAMES)
    path = h._colstore.gen_path(h.id, 0)
    assert path.is_file()
    schema = pq.read_schema(path)
    theta_t = schema.field("theta").type
    assert theta_t.list_size == 2
    assert str(theta_t.value_type) == "halffloat"
    assert str(schema.field("distance").type) == "halffloat"
    assert str(schema.field("w").type) == "double"
    # and the float64 read is the exact upcast of the stored half floats
    df, _ = h.get_distribution(0, 0)
    vals = df.to_numpy()
    assert np.array_equal(vals, vals.astype(np.float16).astype(np.float64))


@needs_pyarrow
def test_columnar_bytes_per_particle_and_ingest_metrics(tmp_path):
    from pyabc_tpu.observability import MetricsRegistry, Tracer
    from pyabc_tpu.observability.metrics import (
        HISTORY_BYTES_ON_DISK_GAUGE,
        HISTORY_INGEST_ROWS_PER_SEC_GAUGE,
    )

    tracer = Tracer()
    reg = MetricsRegistry()
    h = History(f"sqlite+columnar:///{tmp_path}/m.db",
                tracer=tracer, metrics=reg)
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    h.append_population(0, 1.0, _as_batch(_fetch_arrays(2)),
                        3 * N, MODEL_NAMES)
    snap = reg.snapshot()
    assert snap[HISTORY_BYTES_ON_DISK_GAUGE] > 0
    assert HISTORY_INGEST_ROWS_PER_SEC_GAUGE in snap
    assert h.last_ingest["rows"] == N
    # n=120 with d=2 f16 theta + f16 distance + f64 w + i32 m + S=3 f16
    # sumstats is ~24 B/row payload; parquet framing amortizes at real
    # population sizes, so just bound the small-n overhead sanely
    assert h.last_ingest["bytes_on_disk"] < 200 * N


def test_row_store_never_needs_pyarrow(tmp_path, monkeypatch):
    """The gating contract's other half: default-store appends + reads
    work with pyarrow 'absent' (has_pyarrow forced False)."""
    import pyabc_tpu.storage.bytes_storage as bs

    monkeypatch.setattr(bs, "_has_parquet", lambda: False)
    h = History(f"sqlite:///{tmp_path}/r.db")
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    arrs = _fetch_arrays(3)
    h.append_population(0, 1.0, _as_population(arrs), 3 * N, MODEL_NAMES)
    df, w = h.get_distribution(0, 0)
    assert len(df) == int((_as_population(arrs).ms == 0).sum())


def test_columnar_without_pyarrow_raises_informative(tmp_path, monkeypatch):
    import pyabc_tpu.storage.bytes_storage as bs

    if os.environ.get("PYABC_TPU_BLOCK_PYARROW") != "1":
        # simulate absence in-process (the CI leg proves the real thing)
        monkeypatch.setattr(bs, "_has_parquet", lambda: False)
        import pyabc_tpu.storage.columnar as col

        real_import = __builtins__["__import__"] if isinstance(
            __builtins__, dict) else __builtins__.__import__

        def _no_pyarrow(name, *a, **k):
            if name.split(".")[0] == "pyarrow":
                raise ImportError("No module named 'pyarrow'")
            return real_import(name, *a, **k)

        monkeypatch.setattr("builtins.__import__", _no_pyarrow)
    with pytest.raises(ImportError, match="pyarrow"):
        History(f"sqlite+columnar:///{tmp_path}/x.db")
    with pytest.raises(ImportError, match="row store"):
        History(f"sqlite:///{tmp_path}/y.db", store="columnar")


def test_bad_store_value_rejected(tmp_path):
    with pytest.raises(ValueError, match="rows.*columnar"):
        History(f"sqlite:///{tmp_path}/z.db", store="parquet")


# ==================================================== durability contracts
@needs_pyarrow
def test_prune_from_deletes_generation_files(tmp_path):
    _, hc = _open_pair(tmp_path, gens=3)
    run_dir = hc._colstore.run_dir(hc.id)
    assert sorted(p.name for p in run_dir.glob("*.parquet")) == [
        "t0.parquet", "t1.parquet", "t2.parquet"]
    assert hc.prune_from(1) == 2
    assert hc.max_t == 0
    assert [p.name for p in run_dir.glob("*.parquet")] == ["t0.parquet"]
    df, w = hc.get_distribution(0, 0)  # survivor intact
    assert len(df) > 0
    # re-append over the pruned range (the resume seam's re-run)
    arrs = _fetch_arrays(seed=999)
    hc.append_population(1, 0.85, _as_batch(arrs), 3 * N, MODEL_NAMES)
    assert hc.max_t == 1
    df1, _ = hc.get_distribution(0, 1)
    assert len(df1) == int((np.sort(arrs["ms"]) == 0).sum())


@needs_pyarrow
def test_plain_history_url_reads_columnar_run(tmp_path):
    """Reads auto-detect per generation: re-opening a columnar-written
    db WITHOUT the scheme (serving parity helpers do this) works."""
    _, hc = _open_pair(tmp_path, gens=2)
    h2 = History(f"sqlite:///{tmp_path}/col.db")
    assert not h2.columnar  # writes would go to rows; reads still branch
    for t in range(2):
        df_a, w_a = hc.get_distribution(0, t)
        df_b, w_b = h2.get_distribution(0, t)
        assert np.array_equal(df_a.to_numpy(), df_b.to_numpy())
        assert np.array_equal(w_a, w_b)


@needs_pyarrow
def test_columnar_async_writer_and_flush(tmp_path):
    """The packed batch rides the existing _AsyncWriter contract:
    queued appends drain in order, flush() makes them all visible."""
    h = History(f"sqlite+columnar:///{tmp_path}/aw.db")
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    h.start_async_writer()
    for t in range(4):
        h.append_population_async(t, 1.0 - 0.1 * t,
                                  _as_batch(_fetch_arrays(t)),
                                  3 * N, MODEL_NAMES)
    h.flush()
    assert h.n_populations == 4
    h.done()


@needs_pyarrow
def test_columnar_store_sum_stats_policy(tmp_path):
    h = History(f"sqlite+columnar:///{tmp_path}/ss.db",
                store_sum_stats=False)
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    h.append_population(0, 1.0, _as_batch(_fetch_arrays(4)),
                        3 * N, MODEL_NAMES)
    with pytest.raises(ValueError, match="store_sum_stats"):
        h.get_weighted_sum_stats(0)
    df, _ = h.get_distribution(0, 0)  # parameters unaffected
    assert len(df) > 0


# =============================================== row-store satellite fixes
def test_wal_pragmas_applied_and_optional(tmp_path):
    h = History(f"sqlite:///{tmp_path}/w.db")
    assert h._conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    assert h._conn.execute("PRAGMA synchronous").fetchone()[0] == 1  # NORMAL
    h.close()
    h2 = History(f"sqlite:///{tmp_path}/now.db", wal=False)
    assert h2._conn.execute(
        "PRAGMA journal_mode").fetchone()[0] == "delete"
    h2.close()


def test_multi_model_append_single_id_scan(tmp_path):
    """The hoisted MAX(id) allocation: a K=2 append issues ONE particle
    id scan and still produces collision-free ids for both models."""
    h = History(f"sqlite:///{tmp_path}/k2.db")
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                         MODEL_NAMES, "{}", "{}", "{}")
    seen = []
    orig = h._conn.execute

    def spy(sql, *a):
        if "MAX(id), 0) FROM particles" in sql:
            seen.append(sql)
        return orig(sql, *a)

    # the scan goes through the cursor; count via sqlite3 trace instead
    h._conn.set_trace_callback(
        lambda s: seen.append(s) if "MAX(id)" in s else None)
    arrs = _fetch_arrays(6)
    h.append_population(0, 1.0, _as_population(arrs), 3 * N, MODEL_NAMES)
    h._conn.set_trace_callback(None)
    assert len(seen) == 1, seen
    # both models' particles landed with unique ids
    ids = [r[0] for r in h._conn.execute("SELECT id FROM particles")]
    assert len(ids) == len(set(ids)) == N + 1  # + the PRE_TIME particle
    for m in (0, 1):
        df, _ = h.get_distribution(m, 0)
        assert len(df) == int((arrs["ms"] == m).sum())


# ===================================================== end-to-end contract
def _fused_abc(seed=7, pop=150, G=4):
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                     population_size=pop, eps=pt.MedianEpsilon(),
                     seed=seed, fused_generations=G)


@needs_pyarrow
def test_fused_run_bit_identical_across_stores(tmp_path):
    """The acceptance criterion: same seed, one run per store — the
    stored posteriors, weights and epsilon trails are bit-identical,
    with the columnar run ingesting straight from the packed fetch."""
    gens = 6
    abc_r = _fused_abc()
    abc_r.new(f"sqlite:///{tmp_path}/rows.db", {"x": 1.2})
    h_r = abc_r.run(max_nr_populations=gens)
    abc_c = _fused_abc()
    abc_c.new(f"sqlite+columnar:///{tmp_path}/col.db", {"x": 1.2})
    h_c = abc_c.run(max_nr_populations=gens)
    assert h_c.columnar
    # the columnar run actually wrote generation files (packed path)
    assert len(list(h_c._colstore.run_dir(h_c.id).glob("*.parquet"))) == gens
    eps_r = h_r.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps_c = h_c.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert np.array_equal(eps_r, eps_c)
    for t in range(gens):
        df_r, w_r = h_r.get_distribution(0, t)
        df_c, w_c = h_c.get_distribution(0, t)
        assert np.array_equal(df_r.to_numpy(), df_c.to_numpy()), t
        assert np.array_equal(w_r, w_c), t
        ws_r, st_r = h_r.get_weighted_sum_stats(t)
        ws_c, st_c = h_c.get_weighted_sum_stats(t)
        assert np.array_equal(ws_r, ws_c) and np.array_equal(st_r, st_c), t


@needs_pyarrow
def test_history_resume_on_columnar_store(tmp_path):
    """Generation-granularity resume (load -> _restore_state) reads the
    adaptive state back through the columnar branch and continues."""
    db = f"sqlite+columnar:///{tmp_path}/res.db"
    abc1 = _fused_abc()
    abc1.new(db, {"x": 1.2})
    h1 = abc1.run(max_nr_populations=4)
    abc2 = _fused_abc()
    abc2.load(db, h1.id)
    h2 = abc2.run(max_nr_populations=7)
    assert h2.n_populations == 7
    pops = h2.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(pops) == list(range(7))
