"""Driver-proof test: run ``dryrun_multichip`` exactly the way the driver does.

The driver sets ``JAX_PLATFORMS=cpu`` plus
``--xla_force_host_platform_device_count=N`` in the environment of a fresh
process and calls ``dryrun_multichip(N)``.  The axon TPU plugin ignores
``JAX_PLATFORMS``, so the dry run itself must pin every unsharded op to the
CPU pool — the rounds-1/2 MULTICHIP failure was unsharded ops (key
derivation, transition fits, scalar uploads) dispatching to a broken TPU
backend while the mesh itself was already CPU-based.  This test asserts both
OK lines AND that the default device ended up pinned to the CPU platform.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = (
    "import __graft_entry__ as ge; ge.dryrun_multichip(8); "
    "import jax; d = jax.config.jax_default_device; "
    "print('default_device_platform:', None if d is None else d.platform)"
)


@pytest.mark.slow
def test_dryrun_multichip_as_driver():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip OK" in proc.stdout
    assert "fused-chunk OK" in proc.stdout
    assert "default_device_platform: cpu" in proc.stdout
