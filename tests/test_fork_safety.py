"""Fork-safety guard + spawn-context multicore sampling (round-2 weak #7).

The round-1 multicore hang was a forked child touching the parent's
initialized XLA backend. The fix keeps the host proposal path JAX-free;
these tests make that invariant enforced rather than hoped-for.
"""
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.utils.fork_safety import assert_fork_safe, find_jax_refs


def _noisy_model(par):
    return {"y": par["mu"] + 0.3 * np.random.normal()}


def _make_abc(sampler):
    np.random.seed(7)
    return pt.ABCSMC(
        pt.SimpleModel(_noisy_model),
        pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0)),
        pt.PNormDistance(p=2), population_size=24,
        eps=pt.QuantileEpsilon(initial_epsilon=2.0, alpha=0.5),
        sampler=sampler,
    )


def test_find_jax_refs_catches_captured_device_array():
    import jax.numpy as jnp

    trap = jnp.asarray([1.0, 2.0])

    def simulate_one():
        return float(trap.sum())

    refs = find_jax_refs(simulate_one)
    assert refs and "trap" in refs[0]
    with pytest.raises(RuntimeError, match="captures JAX state"):
        assert_fork_safe(simulate_one)


def test_find_jax_refs_catches_nested_attribute():
    import jax.numpy as jnp

    class Dist:
        def __init__(self):
            self.weights = {"y": jnp.float32(1.0)}

    d = Dist()

    def simulate_one():
        return d.weights

    refs = find_jax_refs(simulate_one)
    assert refs and ".weights" in refs[0]


@pytest.mark.filterwarnings("ignore:os.fork:RuntimeWarning")
@pytest.mark.filterwarnings("ignore:This process:DeprecationWarning")
def test_host_closure_passes_guard_both_generations():
    """The real proposal closure (t=0 prior mode AND t>0 transition mode)
    must contain zero jax references — enforced every generation by the
    (opt-in) fork-context multicore samplers before they fork."""
    abc = _make_abc(pt.MulticoreEvalParallelSampler(n_procs=2,
                                                    start_method="fork"))
    abc.new("sqlite://", {"y": 0.5})
    h = abc.run(max_nr_populations=2)  # t=0 (prior) + t=1 (transition)
    assert h.n_populations == 2


def test_guard_failure_is_loud_not_a_deadlock():
    """A deliberately poisoned distance (device array in its state) must
    abort with the offending path, not hang the forked children."""
    import jax.numpy as jnp

    class PoisonedDistance(pt.PNormDistance):
        def initialize(self, *args, **kwargs):
            super().initialize(*args, **kwargs)
            self.poison = jnp.ones(3)

    np.random.seed(7)
    abc = pt.ABCSMC(
        pt.SimpleModel(_noisy_model),
        pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0)),
        PoisonedDistance(p=2), population_size=10,
        eps=pt.QuantileEpsilon(initial_epsilon=2.0, alpha=0.5),
        sampler=pt.MulticoreEvalParallelSampler(n_procs=2,
                                                start_method="fork"),
    )
    abc.new("sqlite://", {"y": 0.5})
    with pytest.raises(RuntimeError, match="poison"):
        abc.run(max_nr_populations=1)


@pytest.mark.slow
@pytest.mark.parametrize("sampler_cls", [
    pt.MulticoreEvalParallelSampler, pt.MulticoreParticleParallelSampler,
])
def test_spawn_context_sampler_recovers_posterior(sampler_cls):
    """start_method='spawn' is immune to forked-backend deadlocks by
    construction: the closure travels via cloudpickle into fresh
    interpreters. Posterior must match the single-core oracle's scale."""
    abc = _make_abc(sampler_cls(n_procs=2, start_method="spawn"))
    abc.new("sqlite://", {"y": 0.5})
    h = abc.run(max_nr_populations=3)
    df, w = h.get_distribution()
    mean = float(np.average(df["mu"], weights=w))
    assert h.n_populations == 3
    assert abs(mean - 0.5) < 0.6  # generous: tiny population
    assert abc.sampler.nr_evaluations_ > 0
