"""Database schema migration tests (reference parity: ``test/migrate/`` —
old db schema versions must still load).

The fixture db is built with the ORIGINAL round-1 schema (no ``telemetry``
column on populations) plus hand-inserted rows; opening it through History
must migrate in place and serve every read API, and a resumed run must
append to it.
"""
import sqlite3

import jax
import numpy as np

import pyabc_tpu as pt

OLD_SCHEMA = """
CREATE TABLE abc_smc (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    start_time TEXT,
    json_parameters TEXT,
    distance_function TEXT,
    epsilon_function TEXT,
    population_strategy TEXT
);
CREATE TABLE populations (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    abc_smc_id INTEGER REFERENCES abc_smc(id),
    t INTEGER,
    population_end_time TEXT,
    nr_samples INTEGER,
    epsilon REAL
);
CREATE TABLE models (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    population_id INTEGER REFERENCES populations(id),
    m INTEGER,
    name TEXT,
    p_model REAL
);
CREATE TABLE particles (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_id INTEGER REFERENCES models(id),
    w REAL,
    distance REAL
);
CREATE TABLE parameters (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    particle_id INTEGER REFERENCES particles(id),
    name TEXT,
    value REAL
);
CREATE TABLE samples (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    particle_id INTEGER REFERENCES particles(id),
    name TEXT,
    value BLOB
);
"""


def _make_old_db(path: str) -> None:
    from pyabc_tpu.storage.bytes_storage import np_to_bytes

    conn = sqlite3.connect(path)
    conn.executescript(OLD_SCHEMA)
    cur = conn.cursor()
    cur.execute(
        "INSERT INTO abc_smc (start_time, json_parameters, distance_function,"
        " epsilon_function, population_strategy) VALUES (?,?,?,?,?)",
        ("2025-01-01T00:00:00", "{}", "{}", "{}", "{}"),
    )
    abc_id = cur.lastrowid
    rng = np.random.default_rng(0)
    for t, eps in [(-1, np.inf), (0, 1.2), (1, 0.6)]:
        cur.execute(
            "INSERT INTO populations (abc_smc_id, t, population_end_time, "
            "nr_samples, epsilon) VALUES (?,?,?,?,?)",
            (abc_id, t, "2025-01-01T00:01:00", 100, float(eps)),
        )
        pop_id = cur.lastrowid
        cur.execute(
            "INSERT INTO models (population_id, m, name, p_model) "
            "VALUES (?,?,?,?)", (pop_id, 0, "gauss", 1.0),
        )
        model_id = cur.lastrowid
        n = 1 if t == -1 else 50
        for _ in range(n):
            theta = float(rng.normal(0.8, 0.4))
            cur.execute(
                "INSERT INTO particles (model_id, w, distance) "
                "VALUES (?,?,?)", (model_id, 1.0 / n, abs(theta - 0.8)),
            )
            pid = cur.lastrowid
            cur.execute(
                "INSERT INTO parameters (particle_id, name, value) "
                "VALUES (?,?,?)", (pid, "theta", theta),
            )
            cur.execute(
                "INSERT INTO samples (particle_id, name, value) "
                "VALUES (?,?,?)",
                (pid, "__flat__" if t >= 0 else "x",
                 np_to_bytes(np.asarray([theta]))),
            )
    conn.commit()
    conn.close()


def test_old_schema_migrates_and_reads(tmp_path):
    db_file = tmp_path / "old.db"
    _make_old_db(str(db_file))
    h = pt.History(f"sqlite:///{db_file}")
    # telemetry column was added in place
    cols = [r[1] for r in h._conn.execute("PRAGMA table_info(populations)")]
    assert "telemetry" in cols
    assert h.max_t == 1
    assert h.n_populations == 2
    df, w = h.get_distribution(0, 1)
    assert len(df) == 50 and abs(w.sum() - 1.0) < 1e-9
    assert h.get_parameter_names(0) == ["theta"]
    assert h.get_telemetry(1) == {}
    pops = h.get_all_populations()
    assert list(pops[pops.t >= 0]["epsilon"]) == [1.2, 0.6]


def test_old_schema_resume_appends(tmp_path):
    db_file = tmp_path / "old_resume.db"
    _make_old_db(str(db_file))

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                    population_size=50, eps=pt.MedianEpsilon(), seed=3)
    abc.load(f"sqlite:///{db_file}", 1, observed_sum_stat={"x": 1.0})
    h = abc.run(max_nr_populations=4)
    assert h.n_populations == 4
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert (np.diff(eps[1:]) < 0).all()


class TestAsyncWriter:
    """Async persistence lifecycle: errors are sticky, done() retires the
    writer thread (no leak per run), resumed runs get a fresh writer."""

    def test_error_is_sticky_and_drains_without_executing(self):
        import pytest

        from pyabc_tpu.storage.history import _AsyncWriter

        w = _AsyncWriter()
        calls = []

        def boom():
            raise RuntimeError("persist failed")

        w.submit(boom)
        with pytest.raises(RuntimeError, match="persist failed"):
            w.flush()
        # still sticky after being raised once
        with pytest.raises(RuntimeError, match="persist failed"):
            w.submit(calls.append, 1)
        # nothing queued after the failure ever executes
        assert calls == []
        with pytest.raises(RuntimeError, match="persist failed"):
            w.close()

    def test_done_retires_writer_thread(self):
        import threading

        h = pt.History("sqlite://")
        before = threading.active_count()
        h.start_async_writer()
        assert threading.active_count() == before + 1
        h.done()
        assert h._writer is None
        # lazily recreated for a resumed run
        h.start_async_writer()
        h.done()
