"""Multi-generation fused device run (whole-run-on-device) tests.

The fused chunk loop replays the reference per-generation semantics with all
between-generation adaptation on device (DeviceContext.multigen_kernel):
transition refit, adaptive-distance reweighting, quantile epsilon. It must
agree statistically with the per-generation pipelined loop; the device math
is f32 vs the host's f64, so agreement is statistical, not bitwise.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _run(fused_generations, *, distance=None, eps=None, n_gens=5, seed=11,
         pop=400, **kwargs):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(
        _gauss_model(), prior,
        distance if distance is not None else pt.AdaptivePNormDistance(p=2),
        population_size=pop,
        eps=eps if eps is not None else pt.MedianEpsilon(),
        seed=seed, fused_generations=fused_generations, **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=n_gens)
    return abc, h


def test_fused_capability_detected():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                    population_size=100, eps=pt.MedianEpsilon())
    assert abc._fused_chunk_capable()
    # chunking disabled
    abc_off = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                        population_size=100, eps=pt.MedianEpsilon(),
                        fused_generations=1)
    assert not abc_off._fused_chunk_capable()
    # complete-history acceptance is fused-capable with a FIXED distance
    # (the epsilon-min carry) but not with an adaptive one (the host loop
    # keeps the trail-restart semantics)
    abc_k = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                      population_size=100,
                      eps=pt.ListEpsilon([1.0, 0.5]),
                      acceptor=pt.UniformAcceptor(use_complete_history=True))
    assert abc_k._fused_chunk_capable()
    abc_k2 = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                       population_size=100, eps=pt.MedianEpsilon(),
                       acceptor=pt.UniformAcceptor(
                           use_complete_history=True))
    assert not abc_k2._fused_chunk_capable()
    # custom scale function shadowing a builtin name: host path only
    def median_absolute_deviation(samples, x_0=None):
        return 2.0 * np.median(np.abs(samples - np.median(samples, 0)), 0)

    abc_c = pt.ABCSMC(
        _gauss_model(), prior,
        pt.AdaptivePNormDistance(p=2,
                                 scale_function=median_absolute_deviation),
        population_size=100, eps=pt.MedianEpsilon(),
    )
    assert not abc_c._fused_chunk_capable()


def test_fused_matches_pipelined_posterior():
    """Fused chunks vs per-generation loop: same posterior within MC error,
    same epsilon trajectory within f32 tolerance."""
    abc_f, h_f = _run(fused_generations=8, seed=11)
    abc_p, h_p = _run(fused_generations=1, seed=11)
    assert h_f.n_populations == h_p.n_populations
    eps_f = h_f.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps_p = h_p.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    # same seed and same proposal kernels: gen 0 identical, later gens see
    # f32-vs-f64 adaptation drift — trajectories must stay close
    np.testing.assert_allclose(eps_f, eps_p, rtol=0.15)
    df_f, w_f = h_f.get_distribution(0)
    df_p, w_p = h_p.get_distribution(0)
    mu_f = float(np.sum(df_f["theta"] * w_f))
    mu_p = float(np.sum(df_p["theta"] * w_p))
    assert mu_f == pytest.approx(POST_MU, abs=0.3)
    assert mu_f == pytest.approx(mu_p, abs=0.25)
    # adaptive weights mirrored into host state for every generation
    assert set(abc_f.distance_function.weights) >= {1, 2, 3, 4}
    tel = h_f.get_telemetry(2)
    assert tel.get("fused_chunk", 0) >= 2


def test_fused_multiple_chunks_advance():
    """Regression: with more generations than one chunk holds, every chunk
    must carry NEW device results — a replayed chunk shows up as a repeating
    epsilon trajectory and duplicate populations."""
    abc, h = _run(fused_generations=2, n_gens=7, seed=13)
    assert h.n_populations == 7
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    # strictly decreasing across chunk boundaries (t=1..6 adaptive)
    assert (np.diff(eps[1:]) < 0).all(), eps
    # chunk indices advance; generation 0 rides the FIRST chunk
    # (prior-mode first generation, round 5)
    cis = [h.get_telemetry(t).get("chunk_index") for t in range(7)]
    assert cis == [1, 1, 2, 2, 3, 3, 4], cis


def test_fused_fixed_distance_and_list_epsilon():
    abc, h = _run(
        fused_generations=4,
        distance=pt.PNormDistance(p=2),
        eps=pt.ListEpsilon([2.0, 1.0, 0.6, 0.4]),
        n_gens=4, seed=3,
    )
    assert h.n_populations == 4
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps, [2.0, 1.0, 0.6, 0.4], rtol=1e-6)
    df, w = h.get_distribution(0)
    assert float(np.sum(df["theta"] * w)) == pytest.approx(POST_MU, abs=0.35)


def test_fused_respects_min_acceptance_stop():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=100,
                    eps=pt.ListEpsilon([1.0, 1e-4, 1e-5, 1e-6]),
                    seed=5, fused_generations=4)
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=4, min_acceptance_rate=0.05)
    # the tiny thresholds collapse acceptance; the chunk must stop early
    # instead of returning 4 full (garbage) generations
    assert h.n_populations < 4


def test_fused_resume_roundtrip(tmp_path):
    db = f"sqlite:///{tmp_path}/fused.db"
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                    population_size=200, eps=pt.MedianEpsilon(), seed=9,
                    fused_generations=3)
    abc.new(db, {"x": X_OBS})
    h1 = abc.run(max_nr_populations=3)
    n1 = h1.n_populations  # capture BEFORE resume re-populates the db
    abc2 = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                     population_size=200, eps=pt.MedianEpsilon(), seed=9,
                     fused_generations=3)
    abc2.load(db, h1.id)
    # max_nr_populations is an ABSOLUTE generation budget (matches
    # test_inference.py::test_load_and_continue)
    h2 = abc2.run(max_nr_populations=5)
    assert h2.n_populations == n1 + 2
    eps = h2.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert (np.diff(eps[1:]) < 0).all()


def test_fused_multimodel_selection():
    """K=2 tractable pair through the FUSED chunk loop: posterior model
    probabilities must match the analytic marginal-likelihood ratio, and
    the telemetry must prove the chunked path ran."""
    from pyabc_tpu.models import model_selection as msel

    models, priors, analytic = msel.tractable_pair()
    x_obs = 0.7
    abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                    population_size=600, eps=pt.MedianEpsilon(), seed=6,
                    fused_generations=4)
    assert abc._fused_chunk_capable()
    abc.new("sqlite://", {"x": x_obs})
    h = abc.run(max_nr_populations=6)
    assert h.n_populations == 6
    assert h.get_telemetry(3).get("fused_chunk"), "fused path not taken"
    probs = h.get_model_probabilities(h.max_t)["p"]
    truth = analytic(x_obs)
    assert float(probs.get(0, 0.0)) == pytest.approx(truth[0], abs=0.15)
    # both models alive through the run (neither sd is decisively better)
    assert set(int(m) for m in probs.index if probs[m] > 0.05) == {0, 1}


def test_fused_multimodel_matches_pergen_loop():
    """Fused chunks vs the per-generation loop on the SAME K=2 problem:
    epsilon trajectories and model posteriors agree within f32 drift."""
    from pyabc_tpu.models import model_selection as msel

    models, priors, _ = msel.tractable_pair()

    def run(fused):
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=500, eps=pt.MedianEpsilon(),
                        seed=12, fused_generations=4 if fused else 1)
        abc.new("sqlite://", {"x": 0.7})
        return abc.run(max_nr_populations=5)

    h_f, h_p = run(True), run(False)
    eps_f = h_f.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps_p = h_p.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps_f, eps_p, rtol=0.2)
    pf = h_f.get_model_probabilities(h_f.max_t)["p"]
    pp = h_p.get_model_probabilities(h_p.max_t)["p"]
    assert float(pf.get(0, 0.0)) == pytest.approx(
        float(pp.get(0, 0.0)), abs=0.15
    )


def test_fused_local_transition_matches_pergen_loop():
    """LocalTransition rides the fused path: k-NN local-covariance refits
    happen IN-KERNEL (dense pairwise + top_k). Posterior must match the
    per-generation loop with the same transition within MC error."""
    tr_kwargs = dict(transitions=pt.LocalTransition(k_fraction=0.3))
    abc_f, h_f = _run(4, seed=17, pop=300, **tr_kwargs)
    assert h_f.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    abc_p, h_p = _run(1, seed=17, pop=300, **tr_kwargs)
    assert h_f.n_populations == h_p.n_populations
    mu_true = POST_MU
    for h in (h_f, h_p):
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(mu_true, abs=0.3)
    eps_f = h_f.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    eps_p = h_p.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps_f, eps_p, rtol=0.25)


def test_local_transition_device_fit_matches_host_fit():
    """Same particle set: in-kernel device_fit must reproduce the host
    fit's per-particle covariances (f32 vs f64)."""
    import pandas as pd

    rng = np.random.default_rng(3)
    n, dim = 60, 2
    X = pd.DataFrame({"a": rng.normal(0, 1, n), "b": rng.normal(2, 0.5, n)})
    w = np.full(n, 1.0 / n)
    host = pt.LocalTransition(k_fraction=0.3)
    host.fit(X, w)
    k = host._effective_k(n, dim)

    import jax.numpy as jnp

    dev = pt.LocalTransition.device_fit(
        jnp.asarray(np.asarray(X), jnp.float32), jnp.asarray(w, jnp.float32),
        dim=dim, scaling=1.0, k=k,
    )
    np.testing.assert_allclose(
        np.asarray(dev["logdets"]), host._logdets, rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(dev["chols"]), host._chols, rtol=5e-3, atol=5e-3
    )


def _two_stat_model():
    @pt.JaxModel.from_function(["theta"], name="gauss2")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        return {"a": theta[0] + 0.5 * jax.random.normal(k1),
                "b": 2.0 * theta[0] + 1.0 * jax.random.normal(k2)}

    return model


def _check_stored_distances_match_schedule(h, dist, obs):
    """Every persisted generation's distances must equal the host
    distance evaluated at THAT generation (i.e. the kernel used the
    right schedule row)."""
    for t in range(h.max_t + 1):
        wd = np.sort(h.get_weighted_distances(t)["distance"].to_numpy())
        _w, stats = h.get_weighted_sum_stats(t)
        recomputed = np.sort([
            dist({"a": float(s[0]), "b": float(s[1])}, obs, t)
            for s in stats
        ])
        np.testing.assert_allclose(wd, recomputed, rtol=2e-3, atol=1e-5)


def test_fused_pnorm_weight_schedule():
    """PNormDistance(weights={t: ...}) rides fused chunks: the host
    resolves the per-generation device_params into a stacked table and
    the scan indexes its generation's row (round-4 verdict Missing #4).
    Verified by recomputing every generation's persisted distances under
    that generation's host weights, plus posterior parity with the
    per-generation loop."""
    obs = {"a": 1.0, "b": 2.0}
    sched = {0: {"a": 1.0, "b": 1.0}, 2: {"a": 3.0, "b": 0.25},
             4: {"a": 0.5, "b": 2.0}}
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    mus = {}
    for fused in (3, 1):
        dist = pt.PNormDistance(p=2, weights={
            t: dict(w) for t, w in sched.items()
        })
        # f32 wire format: the schedule check recomputes distances from
        # the persisted sumstats at rtol 2e-3 — the default f16 fetch
        # narrowing (audited separately in test_fetch_precision.py) sits
        # exactly at that edge and would blur WHICH weights were used
        abc = pt.ABCSMC(_two_stat_model(), prior, dist,
                        population_size=300, eps=pt.MedianEpsilon(),
                        seed=13, fused_generations=fused,
                        fetch_dtype="float32")
        abc.new("sqlite://", obs)
        h = abc.run(max_nr_populations=6)
        assert h.n_populations == 6
        if fused > 1:
            # (weights are label-coerced at initialize, so the schedule
            # gates are meaningful only after the run started)
            assert abc._fused_chunk_capable()
            assert abc._weight_schedule_fused()
            assert h.get_telemetry(3).get("fused_chunk"), "not fused"
        _check_stored_distances_match_schedule(h, dist, obs)
        df, w = h.get_distribution(0, h.max_t)
        mus[fused] = float(np.sum(df["theta"] * w))
    assert mus[3] == pytest.approx(mus[1], abs=0.3)


def test_fused_aggregated_weight_schedule():
    """AggregatedDistance with scheduled top-level weights (and a
    scheduled sub-distance weight) rides fused chunks via the same
    stacked device_params table."""
    obs = {"a": 1.0, "b": 2.0}
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))

    dist = pt.AggregatedDistance(
        [pt.PNormDistance(p=2, weights={0: {"a": 1.0, "b": 0.0},
                                        3: {"a": 2.0, "b": 0.0}}),
         pt.PNormDistance(p=1)],
        weights={0: [1.0, 1.0], 2: [4.0, 0.1]},
    )
    # f32 wire: the schedule check recomputes distances from persisted
    # sumstats at tight rtol (see test_fused_pnorm_weight_schedule)
    abc = pt.ABCSMC(_two_stat_model(), prior, dist, population_size=300,
                    eps=pt.MedianEpsilon(), seed=17, fused_generations=3,
                    fetch_dtype="float32")
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=6)
    assert h.n_populations == 6
    assert abc._fused_chunk_capable() and abc._weight_schedule_fused()
    assert h.get_telemetry(3).get("fused_chunk"), "not fused"
    # the run's own distance is non-adaptive, so recomputing with it is
    # exactly the host semantics (a fresh instance would not have its
    # label-keyed weights coerced yet)
    _check_stored_distances_match_schedule(h, dist, obs)
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    # both stats inform theta; the conjugate posterior over the combined
    # evidence is near 1 — just assert sane recovery
    assert mu == pytest.approx(0.9, abs=0.4)


def test_local_transition_blocked_knn_matches_dense():
    """The tiled (MXU-decomposition) neighbor search for large
    populations must agree with the dense path AND the host fit: same
    particles, block_rows < n (SURVEY.md §7.3.4 blocked kNN)."""
    import pandas as pd

    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    n, dim = 256, 3
    arr = np.column_stack([
        rng.normal(0, 1, n), rng.normal(2, 0.5, n), rng.normal(-1, 2, n)
    ])
    X = pd.DataFrame(arr, columns=["a", "b", "c"])
    w = np.full(n, 1.0 / n)
    host = pt.LocalTransition(k_fraction=0.25)
    host.fit(X, w)
    k = host._effective_k(n, dim)
    dense = pt.LocalTransition.device_fit(
        jnp.asarray(arr, jnp.float32), jnp.asarray(w, jnp.float32),
        dim=dim, scaling=1.0, k=k,
    )
    blocked = pt.LocalTransition.device_fit(
        jnp.asarray(arr, jnp.float32), jnp.asarray(w, jnp.float32),
        dim=dim, scaling=1.0, k=k, block_rows=64,
    )
    # blocked vs dense: same neighbors up to f32 distance ties -> the
    # covariances agree tightly
    np.testing.assert_allclose(
        np.asarray(blocked["logdets"]), np.asarray(dense["logdets"]),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(blocked["chols"]), np.asarray(dense["chols"]),
        rtol=1e-3, atol=1e-3,
    )
    # and both match the host f64 fit
    np.testing.assert_allclose(
        np.asarray(blocked["logdets"]), host._logdets, rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(blocked["chols"]), host._chols, rtol=5e-3, atol=5e-3
    )


@pytest.mark.slow
def test_local_transition_blocked_vs_host_pop16384():
    """The r5 scale case itself: pop 16384, k_fraction 0.25 (k = 4096).
    The blocked top_k device fit must match a memory-lean host f64
    reference (the in-class host fit materializes an 8.6 GB (n, n, d)
    tensor at this size, so the reference tiles rows), and the threshold
    (radius + strided masked gather) selection must agree with the exact
    fit to its documented subsample tolerance."""
    import jax.numpy as jnp

    rng = np.random.default_rng(42)
    n, dim, k = 16384, 4, 4096
    arr = rng.normal(size=(n, dim)).astype(np.float64)
    arr[:, 1] = arr[:, 1] * 0.5 + 2.0
    arr[:, 3] = arr[:, 3] * 2.0 - 1.0
    w = np.full(n, 1.0 / n, np.float32)

    # memory-lean host reference: tiled exact kNN + per-row covariance,
    # same math as LocalTransition.fit (k-neighbor mean of centered
    # outer products, silverman factor, relative diagonal jitter)
    from pyabc_tpu.transition.util import silverman_rule_of_thumb

    factor = silverman_rule_of_thumb(k, dim)
    norms = (arr * arr).sum(1)
    host_logdets = np.empty(n)
    host_chol_diag = np.empty((n, dim))
    for lo in range(0, n, 2048):
        rows = arr[lo:lo + 2048]
        sq = norms[lo:lo + 2048, None] + norms[None, :] \
            - 2.0 * rows @ arr.T
        nn = np.argpartition(sq, kth=k - 1, axis=1)[:, :k]
        for i in range(rows.shape[0]):
            centered = arr[nn[i]] - rows[i]
            cov = centered.T @ centered / k * factor**2
            tr = np.trace(cov) / dim
            cov += np.eye(dim) * max(tr, 1e-10) * pt.LocalTransition.EPS
            sign, host_logdets[lo + i] = np.linalg.slogdet(cov)
            host_chol_diag[lo + i] = np.diag(np.linalg.cholesky(cov))

    dev = pt.LocalTransition.device_fit(
        jnp.asarray(arr, jnp.float32), jnp.asarray(w),
        dim=dim, scaling=1.0, k=k, selection="topk",
    )
    np.testing.assert_allclose(
        np.asarray(dev["logdets"]), host_logdets, rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.diagonal(np.asarray(dev["chols"]), axis1=1, axis2=2),
        host_chol_diag, rtol=5e-3, atol=5e-3,
    )

    thr = pt.LocalTransition.device_fit(
        jnp.asarray(arr, jnp.float32), jnp.asarray(w),
        dim=dim, scaling=1.0, k=k, selection="threshold",
    )
    # documented tolerance: stride-4 subsample of the 4096-neighbor set
    # estimates each covariance from ~1024 points -> ~sqrt(2/1024) ~ 4.4%
    # per-entry noise, i.e. ~d * 2% ~ 0.06 nats of logdet at d=4
    # (measured median 0.056); the bound leaves ~2x headroom
    diff = np.abs(np.asarray(thr["logdets"]) - host_logdets)
    assert np.median(diff) < 0.12, np.median(diff)
    assert diff.max() < 0.75, diff.max()


@pytest.mark.slow
def test_fused_local_transition_large_population():
    """A fused run with LocalTransition at a population large enough to
    trigger the blocked kNN path (n_cap > 4096) completes and recovers
    the conjugate posterior — the SURVEY §7.3.4 scale requirement."""
    abc, h = _run(3, pop=5000, n_gens=3, seed=5,
                  distance=pt.PNormDistance(p=2),
                  transitions=[pt.LocalTransition(k_fraction=0.02)])
    assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(POST_MU, abs=0.3)
    assert len(df) == 5000


def test_fused_list_population_size():
    """ListPopulationSize rides fused chunks: static shapes are sized for
    the largest generation, smaller generations mask down; the History
    must hold exactly the scheduled particle counts per generation."""
    sched = [200, 300, 150, 250, 100]
    abc, h = _run(4, pop=pt.ListPopulationSize(sched), n_gens=len(sched))
    assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    counts = h.get_nr_particles_per_population()
    for t, n_t in enumerate(sched):
        assert counts[t] == n_t, (t, counts)
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(POST_MU, abs=0.35)


def test_fused_complete_history_acceptor():
    """use_complete_history rides fused chunks: the running min of past
    epsilons is a carry; a deliberately NON-monotone ListEpsilon makes the
    historic bound bite (eps jumps back up at t=2, but particles must
    still satisfy the earlier tighter threshold)."""
    eps_list = [2.0, 0.8, 1.5, 0.6, 0.5]
    kwargs = dict(
        distance=pt.PNormDistance(p=2),
        eps=pt.ListEpsilon(eps_list),
        acceptor=pt.UniformAcceptor(use_complete_history=True),
        n_gens=len(eps_list), pop=300,
    )
    abc_f, h_f = _run(4, seed=31, **kwargs)
    assert h_f.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    abc_u, h_u = _run(1, seed=31, **kwargs)
    assert h_f.n_populations == h_u.n_populations
    # at t=2 (eps back up to 1.5) every accepted distance must still obey
    # the historic min 0.8 — on BOTH paths
    for h in (h_f, h_u):
        wd = h.get_weighted_distances(2)
        assert float(wd["distance"].max()) <= 0.8 + 1e-6
    mu_f = float(np.sum(h_f.get_distribution(0, h_f.max_t)[0]["theta"]
                        * h_f.get_distribution(0, h_f.max_t)[1]))
    mu_u = float(np.sum(h_u.get_distribution(0, h_u.max_t)[0]["theta"]
                        * h_u.get_distribution(0, h_u.max_t)[1]))
    assert mu_f == pytest.approx(mu_u, abs=0.25)


def test_complete_history_with_changing_distance_falls_back():
    """A distance whose space changes between generations (adaptive
    weights OR learned-sumstat refits) restarts the epsilon trail on the
    host; complete-history acceptance must not fuse with either."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(
        _gauss_model(), prior,
        pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
            pt.LinearPredictor())),
        population_size=100, eps=pt.MedianEpsilon(),
        acceptor=pt.UniformAcceptor(use_complete_history=True),
    )
    assert not abc._fused_chunk_capable()


@pytest.mark.parametrize("resume_fused_g", [2, 1])
def test_complete_history_resume_replays_epsilon_trail(tmp_path,
                                                       resume_fused_g):
    """Resume must rebuild the complete-history acceptor's epsilon trail
    from the db: after load(), the historic min equals the min of all
    stored epsilons — on the fused path (resume_fused_g=2) AND the host
    per-generation loop (resume_fused_g=1)."""
    db = f"sqlite:///{tmp_path}/uch.db"
    eps_list = [2.0, 0.8, 1.5, 0.6, 0.5]

    def make(fused_g):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        return pt.ABCSMC(
            _gauss_model(), prior, pt.PNormDistance(p=2),
            population_size=200, eps=pt.ListEpsilon(eps_list),
            acceptor=pt.UniformAcceptor(use_complete_history=True),
            seed=41, fused_generations=fused_g,
        )

    abc = make(2)
    abc.new(db, {"x": X_OBS})
    h1 = abc.run(max_nr_populations=3)  # t = 0, 1, 2 (eps 2.0, 0.8, 1.5)
    abc2 = make(resume_fused_g)
    abc2.load(db, h1.id)
    h2 = abc2.run(max_nr_populations=5)
    # the trail was replayed: min over stored epsilons (0.8) bounded every
    # post-resume generation even though eps itself was higher at t=2
    assert abc2.acceptor._historic_min(3) == pytest.approx(0.8)
    for t in (3, 4):
        wd = h2.get_weighted_distances(t)
        assert float(wd["distance"].max()) <= min(eps_list[: t + 1]) + 1e-6


def test_resume_trail_respects_recorded_distance_changes(tmp_path):
    """The live loops record "distance_changed" per generation; the resume
    replay restarts the trail exactly where the live run did — with an
    adaptive distance (changes every generation) only the LAST threshold
    survives, not the historic min."""
    db = f"sqlite:///{tmp_path}/uch_adaptive.db"
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))

    def make():
        return pt.ABCSMC(
            _gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
            population_size=150, eps=pt.MedianEpsilon(),
            acceptor=pt.UniformAcceptor(use_complete_history=True),
            seed=43,  # host loop: complete-history + adaptive never fuses
        )

    abc = make()
    abc.new(db, {"x": X_OBS})
    h1 = abc.run(max_nr_populations=3)
    assert h1.get_telemetry(1).get("distance_changed") is True
    abc2 = make()
    abc2.load(db, h1.id)
    abc2._restore_state(2)  # run() invokes this before the resumed loop
    # trail restarted at every recorded change: only t_last's threshold
    # remains comparable, exactly as in the uninterrupted run
    eps_lastgen = float(
        h1.get_all_populations().query("t == 2")["epsilon"].iloc[0])
    assert abc2.acceptor._historic_min(3) == pytest.approx(eps_lastgen)
    # and the resumed loop's first generation sees the pending change flag
    assert abc2._resumed_distance_changed is True


def test_fused_aggregated_distance_matches_pergen_loop():
    """Non-adaptive AggregatedDistance (weighted sum of sub-distances)
    rides fused chunks with chunk-constant params; posterior and epsilon
    trajectory must match the per-generation loop."""
    def make_distance():
        return pt.AggregatedDistance(
            [pt.PNormDistance(p=2), pt.PNormDistance(p=1)],
            weights=[1.0, 0.5],
        )

    abc_f, h_f = _run(4, seed=47, pop=300, distance=make_distance())
    assert h_f.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    abc_u, h_u = _run(1, seed=47, pop=300, distance=make_distance())
    assert h_f.n_populations == h_u.n_populations
    eps_f = h_f.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    eps_u = h_u.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps_f, eps_u, rtol=0.2)
    for h in (h_f, h_u):
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(POST_MU, abs=0.3)
    # the adaptive variant with a builtin scale twin ALSO rides chunks
    abc_a = pt.ABCSMC(
        _gauss_model(), pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.AdaptiveAggregatedDistance([pt.PNormDistance(p=2),
                                       pt.PNormDistance(p=1)]),
        population_size=100, eps=pt.MedianEpsilon(),
    )
    assert abc_a._fused_chunk_capable()
    # ... but not with a custom scale function (host-only refits)
    abc_c = pt.ABCSMC(
        _gauss_model(), pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.AdaptiveAggregatedDistance(
            [pt.PNormDistance(p=2), pt.PNormDistance(p=1)],
            scale_function=lambda v: float(np.std(v)),
        ),
        population_size=100, eps=pt.MedianEpsilon(),
    )
    assert not abc_c._fused_chunk_capable()


@pytest.mark.slow
def test_fused_adaptive_aggregated_matches_pergen_loop():
    """AdaptiveAggregatedDistance: the per-generation 1/scale sub-distance
    reweighting runs IN-KERNEL over the record ring. Epsilon trajectory,
    per-generation weights, and posterior must match the host per-
    generation loop statistically."""
    from pyabc_tpu.distance.scale import standard_deviation

    def make_distance(scale_fn=None):
        kw = {} if scale_fn is None else {"scale_function": scale_fn}
        return pt.AdaptiveAggregatedDistance(
            [pt.PNormDistance(p=2), pt.PNormDistance(p=1)], **kw
        )

    for scale_fn in (None, standard_deviation):  # span default + std twin
        abc_f, h_f = _run(4, seed=53, pop=300,
                          distance=make_distance(scale_fn))
        assert h_f.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        abc_u, h_u = _run(1, seed=53, pop=300,
                          distance=make_distance(scale_fn))
        assert h_f.n_populations == h_u.n_populations
        eps_f = h_f.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
        eps_u = h_u.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
        np.testing.assert_allclose(eps_f, eps_u, rtol=0.25)
        # the in-kernel reweighting mirrors into the host weights dict
        w_f = abc_f.distance_function.weights
        w_u = abc_u.distance_function.weights
        shared = sorted(set(w_f) & set(w_u) - {-1})
        assert len(shared) >= 2
        for t in shared:
            np.testing.assert_allclose(
                np.asarray(w_f[t]), np.asarray(w_u[t]), rtol=0.35,
            )
        for h in (h_f, h_u):
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            assert mu == pytest.approx(POST_MU, abs=0.3)


def test_gridsearch_device_fit_matches_host_winner():
    """In-kernel cross-validated bandwidth selection: on the same
    (unpadded) particle set with the same fold rule, the device winner
    must be the host GridSearchCV's best scaling, and the returned params
    must equal an MVN fit at that scaling."""
    import jax.numpy as jnp
    import pandas as pd

    from pyabc_tpu.transition.util import silverman_rule_of_thumb

    rng = np.random.default_rng(5)
    n, dim = 60, 2
    X = pd.DataFrame({"a": rng.normal(0, 1, n),
                      "b": rng.normal(1, 0.4, n)})
    w = rng.uniform(0.5, 1.0, n)
    w = w / w.sum()
    scalings = (0.25, 1.0, 4.0)
    host = pt.GridSearchCV(pt.MultivariateNormalTransition(),
                           {"scaling": list(scalings)}, cv=3)
    host.fit(X, w)
    dev = pt.GridSearchCV.device_fit(
        jnp.asarray(np.asarray(X), jnp.float32),
        jnp.asarray(w, jnp.float32),
        dim=dim, scalings=scalings, cv=3,
        bandwidth_selector=silverman_rule_of_thumb,
    )
    s_host = host.best_params_["scaling"]
    ref = pt.MultivariateNormalTransition(scaling=s_host)
    ref.fit(X, w)
    np.testing.assert_allclose(
        np.asarray(dev["chol"]), ref._chol, rtol=5e-3, atol=5e-3,
    )
    np.testing.assert_allclose(
        float(dev["logdet"]), ref._logdet, rtol=2e-3, atol=2e-3,
    )


def test_fused_gridsearch_transition_runs_and_recovers_posterior():
    """GridSearchCV over the MVN scaling rides fused chunks: the CV fold
    fits and candidate scoring happen inside the multigen kernel."""
    abc, h = _run(
        4, seed=53, pop=300,
        distance=pt.PNormDistance(p=2),
        transitions=pt.GridSearchCV(pt.MultivariateNormalTransition(),
                                    {"scaling": [0.5, 1.0, 2.0]}, cv=3),
    )
    assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(POST_MU, abs=0.3)
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert (np.diff(eps) < 0).all()


def test_gridsearch_nonpositive_scaling_falls_back():
    """A grid containing a non-positive scaling would NaN the in-kernel
    scores; such configs must keep the host path."""
    abc = pt.ABCSMC(
        _gauss_model(), pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.PNormDistance(p=2), population_size=100, eps=pt.MedianEpsilon(),
        transitions=pt.GridSearchCV(pt.MultivariateNormalTransition(),
                                    {"scaling": [0.0, 1.0, 2.0]}),
    )
    assert not abc._fused_chunk_capable()


def test_gridsearch_degenerate_cv_falls_back():
    """cv<2 (or cv larger than the population) behaves differently on the
    host (empty train folds -> first-entry fallback) than the device fold
    rule would; such configs must keep the host path."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    for cv in (1, 10_000):
        abc = pt.ABCSMC(
            _gauss_model(), prior, pt.PNormDistance(p=2),
            population_size=100, eps=pt.MedianEpsilon(),
            transitions=pt.GridSearchCV(pt.MultivariateNormalTransition(),
                                        {"scaling": [0.5, 2.0]}, cv=cv),
        )
        assert not abc._fused_chunk_capable(), cv


def test_fetch_pipeline_depths_complete_all_generations():
    """Every fetch_pipeline_depth (1 = synchronous fetch with the
    speculative next chunk, >1 = threaded pipelined fetches) must run the
    FULL schedule — a depth-1 regression once truncated the run silently
    after the first chunk — and agree with the other depths exactly on
    the epsilon trajectory (same seed, same kernels)."""
    eps_by_depth = {}
    for depth in (1, 2, 3):
        abc, h = _run(3, seed=71, pop=200,
                      distance=pt.PNormDistance(p=2), n_gens=9,
                      fetch_pipeline_depth=depth)
        assert h.n_populations == 9, (
            f"depth {depth} truncated the run at {h.n_populations} gens"
        )
        eps_by_depth[depth] = (
            h.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
        )
    np.testing.assert_allclose(eps_by_depth[1], eps_by_depth[2])
    np.testing.assert_allclose(eps_by_depth[1], eps_by_depth[3])


def test_fused_calibration_matches_host_calibration():
    """The first fused chunk runs calibration IN-KERNEL (round 5): same
    root key stream as the host calibration round, so the epsilon trail,
    initial adaptive weights and posterior are IDENTICAL to the host
    calibration path — and the sampler must see NO calibration call."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    res = {}
    for label, fg in (("fused", 4), ("host", 1)):
        dist = pt.AdaptivePNormDistance(p=2)
        eps = pt.MedianEpsilon()
        # f32 wire format: this test asserts EXACT key-stream parity of
        # the in-kernel calibration against the host path; the default
        # f16 fetch narrowing (audited in test_fetch_precision.py) would
        # round the persisted rows at ~5e-4 and blur the 1e-6 claim
        abc = pt.ABCSMC(_gauss_model(), prior, dist, population_size=300,
                        eps=eps, seed=42, fused_generations=fg,
                        fetch_dtype="float32")
        calib_calls = []
        orig = abc.sampler.sample_until_n_accepted

        def counting(n, spec, t, *a, _orig=orig, _cc=calib_calls, **kw):
            if t == -1:
                _cc.append(n)
            return _orig(n, spec, t, *a, **kw)

        abc.sampler.sample_until_n_accepted = counting
        if fg > 1:
            assert abc._fused_calibration_cfg() == (300, True, True)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=4)
        df, w = h.get_distribution(0, h.max_t)
        res[label] = {
            "mu": float(np.sum(df["theta"] * w)),
            "eps": {t: float(v) for t, v in eps._values.items()},
            "w0": np.asarray(dist.weights[0], np.float64),
            "calib_calls": list(calib_calls),
        }
    assert res["fused"]["calib_calls"] == [], (
        "fused run still paid a host calibration round trip"
    )
    assert res["host"]["calib_calls"] == [300]
    # identical key streams -> identical calibration -> identical run
    assert res["fused"]["eps"].keys() == res["host"]["eps"].keys()
    for t in res["host"]["eps"]:
        assert res["fused"]["eps"][t] == pytest.approx(
            res["host"]["eps"][t], rel=1e-5), t
    np.testing.assert_allclose(res["fused"]["w0"], res["host"]["w0"],
                               rtol=1e-4)
    assert res["fused"]["mu"] == pytest.approx(res["host"]["mu"], abs=1e-6)


def test_drain_async_matches_sync_run():
    """drain_async hands the final in-flight fetches to a background
    thread and returns early; after drain_join the History must be
    IDENTICAL (same seed, same kernels) to the synchronous run, and the
    chunk events must account for every persisted generation."""
    abc_sync, h_sync = _run(3, seed=81, pop=200,
                            distance=pt.PNormDistance(p=2), n_gens=9)
    events = []
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=200, eps=pt.MedianEpsilon(),
                    seed=81, fused_generations=3)
    abc.drain_async = True
    abc.compute_probe = True
    abc.chunk_event_cb = events.append
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=9)
    abc.drain_join()
    assert abc._drain_thread is None
    assert h.n_populations == 9
    eps_sync = h_sync.get_all_populations().query("t >= 0")["epsilon"]
    eps_async = h.get_all_populations().query("t >= 0")["epsilon"]
    np.testing.assert_allclose(eps_async.to_numpy(), eps_sync.to_numpy())
    # events cover all 9 generations (gen 0 + fused chunks) exactly once
    assert sum(e["gens"] for e in events) == 9
    assert sum(e["n_acc"] for e in events) == 9 * 200
    assert all(e["chunk_s"] >= 0 and e["process_s"] >= 0 for e in events)
    # probe recorded one completion per dispatched chunk, timestamps sane
    assert len(abc.probe_events) >= len(events) - 1
    assert all(done >= disp for disp, done in abc.probe_events)
    # a second run on the same object must not trip over drain state
    abc2 = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                     population_size=200, eps=pt.MedianEpsilon(),
                     seed=81, fused_generations=3)
    abc2.drain_async = True
    abc2.new("sqlite://", {"x": X_OBS})
    abc2.adopt_device_context(abc)
    h2 = abc2.run(max_nr_populations=9)
    abc2.drain_join()
    assert h2.n_populations == 9


def test_fused_mid_chunk_stop_rebuilds_deferred_population():
    """A _check_stop stop in the MIDDLE of a chunk (simulation budget)
    hits the deferred-construction path: the newest processed
    generation's Population was shipped to the writer as a builder, so
    the loop must rebuild it for the final transition refit. The run
    must end cleanly with every persisted generation intact."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=200, eps=pt.MedianEpsilon(),
                    seed=82, fused_generations=4)
    abc.new("sqlite://", {"x": X_OBS})
    # a budget that runs out mid-chunk: gen 0 alone costs >= 200 sims
    h = abc.run(max_nr_populations=12, max_total_nr_simulations=1200)
    assert 1 <= h.n_populations < 12
    pops = h.get_all_populations().query("t >= 0")
    assert len(pops) == h.n_populations
    # the final persisted generation is a full, weighted population
    df, w = h.get_distribution(m=0, t=h.max_t)
    assert len(df) == 200 and np.isclose(w.sum(), 1.0)
    # transitions were refit from the (rebuilt) final population
    assert abc.transitions[0].X is not None


@pytest.mark.slow
def test_fused_multimodel_local_transition():
    """K=2 LocalTransition through the fused chunk loop: the host
    _effective_k rule runs IN-KERNEL against each model's dynamic
    accepted count, so per-model masked kNN refits ride chunks. Model
    posterior must match the analytic marginal-likelihood ratio and the
    per-generation loop."""
    from pyabc_tpu.models import model_selection as msel

    models, priors, analytic = msel.tractable_pair()
    x_obs = 0.7

    def run(fused):
        abc = pt.ABCSMC(
            models, priors, pt.PNormDistance(p=2),
            population_size=500, eps=pt.MedianEpsilon(), seed=8,
            fused_generations=4 if fused else 1,
            transitions=[pt.LocalTransition(), pt.LocalTransition()],
        )
        if fused:
            assert abc._fused_chunk_capable()
        abc.new("sqlite://", {"x": x_obs})
        return abc.run(max_nr_populations=5)

    h_f, h_p = run(True), run(False)
    assert h_f.get_telemetry(3).get("fused_chunk"), "fused path not taken"
    truth = analytic(x_obs)
    pf = h_f.get_model_probabilities(h_f.max_t)["p"]
    pp = h_p.get_model_probabilities(h_p.max_t)["p"]
    assert float(pf.get(0, 0.0)) == pytest.approx(truth[0], abs=0.15)
    assert float(pf.get(0, 0.0)) == pytest.approx(
        float(pp.get(0, 0.0)), abs=0.15
    )
    eps_f = h_f.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps_p = h_p.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps_f, eps_p, rtol=0.25)


@pytest.mark.slow
def test_fused_multimodel_gridsearchcv():
    """K=2 GridSearchCV (per-model in-kernel CV bandwidth selection over
    row-indexed folds — declared deviation from the host's per-model
    shuffled folds) through the fused chunk loop."""
    from pyabc_tpu.models import model_selection as msel

    models, priors, analytic = msel.tractable_pair()
    x_obs = 0.7

    def make_tr():
        return pt.GridSearchCV(pt.MultivariateNormalTransition(),
                               {"scaling": [0.5, 1.0, 2.0]}, cv=4)

    abc = pt.ABCSMC(
        models, priors, pt.PNormDistance(p=2),
        population_size=500, eps=pt.MedianEpsilon(), seed=15,
        fused_generations=4, transitions=[make_tr(), make_tr()],
    )
    assert abc._fused_chunk_capable()
    abc.new("sqlite://", {"x": x_obs})
    h = abc.run(max_nr_populations=5)
    assert h.get_telemetry(3).get("fused_chunk"), "fused path not taken"
    truth = analytic(x_obs)
    probs = h.get_model_probabilities(h.max_t)["p"]
    assert float(probs.get(0, 0.0)) == pytest.approx(truth[0], abs=0.15)
    # posterior of the winning model still matches the conjugate truth
    df, w = h.get_distribution(0, h.max_t)
    post_var = 1.0 / (1 / 1.0**2 + 1 / 0.6**2)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(post_var * x_obs / 0.6**2, abs=0.3)


def test_local_device_fit_dynamic_k_matches_masked_host():
    """Per-model masked refit: on lanes where only SOME rows belong to the
    model (zero weights elsewhere), the in-kernel dynamic-k rule must
    reproduce the host fit of just that model's rows."""
    import jax.numpy as jnp
    import pandas as pd

    rng = np.random.default_rng(2)
    n_cap, d = 64, 2
    thetas = rng.normal(size=(n_cap, d)).astype(np.float32)
    # model owns 20 scattered rows
    own = np.zeros(n_cap, bool)
    own[rng.choice(n_cap, 20, replace=False)] = True
    w = np.where(own, 1.0 / 20, 0.0).astype(np.float32)

    tr = pt.LocalTransition()
    host_X = pd.DataFrame(thetas[own], columns=["a", "b"])
    tr.fit(host_X, np.full(20, 1.0 / 20))
    k_host = tr._effective_k(20, d)

    dev = pt.LocalTransition.device_fit(
        jnp.asarray(thetas), jnp.asarray(w), dim=d, scaling=1.0,
        k_cap=32, k_fixed=-1, k_fraction=tr.k_fraction,
    )
    # the dynamic k equals the host rule at c=20 (indirectly: per-row
    # covariances of the model's rows match the host's per-row fit)
    chols_dev = np.asarray(dev["chols"])[own]
    np.testing.assert_allclose(chols_dev, tr._chols, rtol=2e-3, atol=2e-4)
    assert k_host == int(np.clip(round(tr.k_fraction * 20), d + 1, 20))
