"""Unit tests for strategy components vs closed forms.

Mirrors the reference test strategy (SURVEY.md §4): distances, epsilon
schedules, acceptors, transitions each checked against numpy/scipy closed
forms (reference test/base/test_distance.py etc.).
"""
import numpy as np
import pandas as pd
import pytest
import scipy.stats as st

import pyabc_tpu as pt
from pyabc_tpu.distance import scale as scale_mod


class TestPNormDistance:
    def test_euclidean(self):
        d = pt.PNormDistance(p=2)
        x = {"a": 1.0, "b": 2.0}
        x0 = {"a": 0.0, "b": 0.0}
        assert d(x, x0) == pytest.approx(np.sqrt(5.0))

    def test_linf(self):
        d = pt.PNormDistance(p=np.inf)
        assert d({"a": 1.0, "b": -3.0}, {"a": 0.0, "b": 0.0}) == pytest.approx(3.0)

    def test_weights(self):
        spec = pt.SumStatSpec({"a": 0.0, "b": 0.0})
        d = pt.PNormDistance(p=1, weights={"a": 2.0, "b": 0.5},
                             sumstat_spec=spec)
        d.initialize(0, None, {"a": 0.0, "b": 0.0})
        assert d({"a": 1.0, "b": 2.0}, {"a": 0.0, "b": 0.0}) == pytest.approx(3.0)

    def test_device_matches_host(self):
        import jax.numpy as jnp

        spec = pt.SumStatSpec({"a": 0.0, "b": np.zeros(3)})
        d = pt.PNormDistance(p=2, sumstat_spec=spec)
        d.initialize(0, None, {"a": 0.0, "b": np.zeros(3)})
        x = {"a": 1.5, "b": np.array([1.0, -2.0, 0.5])}
        x0 = {"a": 0.0, "b": np.zeros(3)}
        host = d(x, x0)
        dev = d.device_fn(spec)(
            jnp.asarray(spec.flatten(x)), jnp.asarray(spec.flatten(x0)),
            d.device_params(0),
        )
        assert float(dev) == pytest.approx(host, rel=1e-5)


class TestAdaptivePNormDistance:
    def test_reweighting_mad(self):
        spec = pt.SumStatSpec({"a": 0.0, "b": 0.0})
        d = pt.AdaptivePNormDistance(p=2, sumstat_spec=spec,
                                     normalize_weights=False)
        rng = np.random.default_rng(0)
        samples = np.stack([rng.normal(0, 1, 200), rng.normal(0, 10, 200)], 1)
        d.initialize(0, lambda: samples, {"a": 0.0, "b": 0.0})
        w = d.weights[0]
        # statistic with 10x the scale gets ~1/10 the weight
        assert w[0] / w[1] == pytest.approx(10.0, rel=0.35)

    def test_configure_sampler_sets_record_rejected(self):
        d = pt.AdaptivePNormDistance()
        s = pt.SingleCoreSampler()
        d.configure_sampler(s)
        assert s.sample_factory.record_rejected

    def test_update_changes_weights(self):
        spec = pt.SumStatSpec({"a": 0.0})
        d = pt.AdaptivePNormDistance(sumstat_spec=spec)
        d.initialize(0, lambda: np.random.default_rng(0).normal(
            size=(100, 1)), {"a": 0.0})
        changed = d.update(1, lambda: np.random.default_rng(1).normal(
            0, 5, size=(100, 1)))
        assert changed
        assert 0 in d.weights and 1 in d.weights


class TestScaleFunctions:
    def test_values(self):
        rng = np.random.default_rng(0)
        s = rng.normal(2.0, 3.0, size=(5000, 1))
        x0 = np.array([2.0])
        assert scale_mod.standard_deviation(s) == pytest.approx(3.0, rel=0.1)
        assert scale_mod.median_absolute_deviation(s) == pytest.approx(
            3.0 * 0.6745, rel=0.1)
        assert scale_mod.bias(s, x0)[0] < 0.2
        assert scale_mod.root_mean_square_deviation(s, x0) == pytest.approx(
            3.0, rel=0.1)
        assert scale_mod.span(s)[0] > 10


class TestEpsilon:
    def test_constant(self):
        eps = pt.ConstantEpsilon(42.0)
        assert eps(0) == 42.0 and eps(7) == 42.0

    def test_list(self):
        eps = pt.ListEpsilon([3.0, 2.0, 1.0])
        assert eps(1) == 2.0

    def test_quantile_weighted(self):
        eps = pt.QuantileEpsilon(initial_epsilon=10.0, alpha=0.5)
        eps.initialize(0)
        assert eps(0) == 10.0
        df = pd.DataFrame({"distance": [1.0, 2.0, 3.0, 4.0],
                           "w": [0.7, 0.1, 0.1, 0.1]})
        eps.update(1, lambda: df)
        # cumw: 0.7 at d=1 -> weighted median = 1
        assert eps(1) == pytest.approx(1.0)

    def test_median_from_sample(self):
        eps = pt.MedianEpsilon()
        assert eps.requires_calibration()
        df = pd.DataFrame({"distance": np.arange(1.0, 11.0),
                           "w": np.full(10, 0.1)})
        eps.initialize(0, get_weighted_distances=lambda: df)
        assert 4.0 <= eps(0) <= 6.0


class TestAcceptor:
    def test_uniform(self):
        acc = pt.UniformAcceptor()
        dist = pt.PNormDistance(p=2)
        eps = pt.ConstantEpsilon(1.0)
        res = acc(dist, eps, {"a": 0.5}, {"a": 0.0}, 0, None)
        assert res.accept and res.distance == pytest.approx(0.5)
        res = acc(dist, eps, {"a": 2.0}, {"a": 0.0}, 0, None)
        assert not res.accept


class TestMVNTransition:
    def test_fit_rvs_pdf(self):
        rng = np.random.default_rng(0)
        X = pd.DataFrame({"a": rng.normal(0, 1, 400),
                          "b": rng.normal(5, 2, 400)})
        w = np.full(400, 1 / 400)
        tr = pt.MultivariateNormalTransition()
        tr.fit(X, w)
        draws = tr.rvs(2000)
        assert np.abs(draws["a"].mean()) < 0.2
        assert np.abs(draws["b"].mean() - 5) < 0.4
        # pdf integrates against samples sensibly: compare with scipy KDE value
        p = tr.pdf(pd.Series({"a": 0.0, "b": 5.0}))
        assert p > 0

    def test_pdf_matches_manual_mixture(self):
        X = pd.DataFrame({"a": [0.0, 1.0]})
        w = np.array([0.5, 0.5])
        tr = pt.MultivariateNormalTransition()
        tr.fit(X, w)
        cov = tr.cov[0, 0]
        x = 0.3
        expect = 0.5 * (
            st.norm.pdf(x, 0, np.sqrt(cov)) + st.norm.pdf(x, 1, np.sqrt(cov))
        )
        assert tr.pdf(pd.Series({"a": x})) == pytest.approx(expect, rel=1e-6)

    def test_device_matches_host(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        X = pd.DataFrame({"a": rng.normal(0, 1, 50),
                          "b": rng.normal(2, 1, 50)})
        w = rng.uniform(0.5, 1.5, 50)
        w /= w.sum()
        tr = pt.MultivariateNormalTransition()
        tr.fit(X, w)
        params = tr.device_params()
        theta = jnp.asarray([0.5, 2.5])
        dev = float(tr.device_logpdf(theta, params))
        host = float(np.log(tr.pdf(pd.Series({"a": 0.5, "b": 2.5}))))
        assert dev == pytest.approx(host, rel=1e-3)  # f32 device vs f64 host

    def test_not_enough_particles(self):
        tr = pt.MultivariateNormalTransition()
        with pytest.raises(pt.NotEnoughParticles):
            tr.fit(pd.DataFrame({"a": []}), np.array([]))


class TestLocalTransition:
    def test_fit_rvs_pdf(self):
        rng = np.random.default_rng(0)
        X = pd.DataFrame({"a": rng.normal(0, 1, 100),
                          "b": rng.normal(0, 1, 100)})
        w = np.full(100, 0.01)
        tr = pt.LocalTransition(k_fraction=0.3)
        tr.fit(X, w)
        s = tr.rvs_single()
        assert set(s.index) == {"a", "b"}
        assert tr.pdf(pd.Series({"a": 0.0, "b": 0.0})) > 0

    def test_device_matches_host(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        X = pd.DataFrame({"a": rng.normal(0, 1, 40)})
        w = np.full(40, 1 / 40)
        tr = pt.LocalTransition(k=10)
        tr.fit(X, w)
        dev = float(tr.device_logpdf(jnp.asarray([0.2]), tr.device_params()))
        host = float(np.log(tr.pdf(pd.Series({"a": 0.2}))))
        assert dev == pytest.approx(host, rel=1e-3)  # f32 device vs f64 host


class TestDiscreteTransitions:
    def test_random_walk(self):
        X = pd.DataFrame({"k": [3.0] * 10})
        w = np.full(10, 0.1)
        tr = pt.DiscreteRandomWalkTransition()
        tr.fit(X, w)
        s = tr.rvs_single()
        assert s["k"] in (2.0, 3.0, 4.0)
        # pmf sums to 1 over reachable points
        total = sum(float(np.atleast_1d(tr.pdf(pd.Series({"k": v})))[0])
                    for v in [2.0, 3.0, 4.0])
        assert total == pytest.approx(1.0)

    def test_jump(self):
        X = pd.DataFrame({"k": [1.0] * 5 + [2.0] * 5})
        w = np.full(10, 0.1)
        tr = pt.DiscreteJumpTransition(domain=[1.0, 2.0, 3.0], p_stay=0.7)
        tr.fit(X, w)
        p1 = tr.pdf(pd.Series({"k": 1.0}))
        # stay on 1 (mass .5 * .7) + jump from 2 (mass .5 * .15)
        assert p1 == pytest.approx(0.5 * 0.7 + 0.5 * 0.15)


class TestModelPerturbationKernel:
    def test_pmf_rows_normalized(self):
        mpk = pt.ModelPerturbationKernel(3, probability_to_stay=0.7)
        for m in range(3):
            assert sum(mpk.pmf(n, m) for n in range(3)) == pytest.approx(1.0)
        assert mpk.pmf(1, 1) == pytest.approx(0.7)
        assert mpk.pmf(0, 1) == pytest.approx(0.15)


class TestGridSearchCV:
    def test_picks_reasonable_scaling(self):
        rng = np.random.default_rng(0)
        X = pd.DataFrame({"a": rng.normal(0, 1, 120)})
        w = np.full(120, 1 / 120)
        gs = pt.GridSearchCV(pt.MultivariateNormalTransition(),
                             {"scaling": [0.1, 1.0, 10.0]}, cv=3)
        gs.fit(X, w)
        assert gs.best_params_["scaling"] in (0.1, 1.0)
        assert gs.pdf(pd.Series({"a": 0.0})) > 0


class TestStochasticKernels:
    def test_normal_kernel_matches_scipy(self):
        k = pt.NormalKernel(cov=np.diag([1.0, 4.0]))
        x0 = {"a": 0.0, "b": 0.0}
        k.initialize(0, None, x0)
        x = {"a": 1.0, "b": 2.0}
        expect = st.multivariate_normal.logpdf([1.0, 2.0], [0, 0],
                                               np.diag([1.0, 4.0]))
        assert k(x, x0) == pytest.approx(expect)
        assert k.pdf_max == pytest.approx(
            st.multivariate_normal.logpdf([0, 0], [0, 0], np.diag([1.0, 4.0]))
        )

    def test_independent_normal(self):
        k = pt.IndependentNormalKernel(var=[1.0, 4.0])
        x0 = {"a": 0.0, "b": 0.0}
        k.initialize(0, None, x0)
        expect = (st.norm.logpdf(1.0, 0, 1) + st.norm.logpdf(2.0, 0, 2))
        assert k({"a": 1.0, "b": 2.0}, x0) == pytest.approx(expect)

    def test_poisson(self):
        k = pt.PoissonKernel()
        x0 = {"n": 3.0}
        k.initialize(0, None, x0)
        assert k({"n": 2.5}, x0) == pytest.approx(st.poisson.logpmf(3, 2.5))

    def test_binomial(self):
        k = pt.BinomialKernel(p=0.3)
        x0 = {"n": 2.0}
        k.initialize(0, None, x0)
        assert k({"n": 10.0}, x0) == pytest.approx(st.binom.logpmf(2, 10, 0.3))


class TestHistory:
    def test_roundtrip(self, tmp_path):
        db = f"sqlite:///{tmp_path}/test.db"
        spaces = [pt.ParameterSpace(["a", "b"])]
        spec = pt.SumStatSpec({"s": 0.0})
        pop = pt.Population(
            ms=np.zeros(10, np.int32),
            thetas=np.random.default_rng(0).normal(size=(10, 2)),
            weights=np.full(10, 0.1),
            distances=np.linspace(0, 1, 10),
            sumstats=np.random.default_rng(1).normal(size=(10, 1)),
            spaces=spaces, sumstat_spec=spec, model_names=["m0"],
        )
        h = pt.History(db)
        h.store_initial_data(0, {}, {"s": 1.5}, {"a": 0.3}, ["m0"],
                             "{}", "{}", "{}")
        h.append_population(0, 0.9, pop, 123, ["m0"])
        h.append_population(1, 0.5, pop, 456, ["m0"])
        assert h.max_t == 1
        assert h.n_populations == 2
        assert h.total_nr_simulations == 579
        df, w = h.get_distribution(0, 1)
        assert df.shape == (10, 2) and set(df.columns) == {"a", "b"}
        assert w.sum() == pytest.approx(1.0)
        probs = h.get_model_probabilities(1)
        assert probs.loc[0, "p"] == pytest.approx(1.0)
        wd = h.get_weighted_distances(1)
        assert wd["w"].sum() == pytest.approx(1.0)
        ws, stats = h.get_weighted_sum_stats(0)
        assert stats.shape == (10, 1)
        obs = h.get_observed_sum_stat()
        assert obs["s"] == pytest.approx(1.5)
        assert h.get_ground_truth_parameter()["a"] == pytest.approx(0.3)
        pops = h.get_all_populations()
        assert list(pops["t"]) == [-1, 0, 1]
        # second run on the same db gets a fresh id
        h2 = pt.History(db)
        h2.store_initial_data(0, {}, {"s": 2.0}, {}, ["m0"], "{}", "{}", "{}")
        assert h2.id == h.id + 1
        assert h2.max_t == -1


class TestDiscreteInferenceLoop:
    def test_discrete_parameter_recovered_end_to_end(self):
        """Full ABC run over a DISCRETE parameter: randint prior +
        DiscreteJumpTransition proposals (host path — discrete kernels are
        host-only by design). The posterior must concentrate on the true
        grid point."""
        domain = list(range(1, 9))
        true_k = 5.0

        def model(par):
            return {"y": par["k"] + 0.2 * np.random.normal()}

        np.random.seed(3)
        abc = pt.ABCSMC(
            pt.SimpleModel(model),
            pt.Distribution(k=pt.RV("randint", 1, 9)),
            pt.PNormDistance(p=2), population_size=150,
            eps=pt.QuantileEpsilon(initial_epsilon=3.0, alpha=0.5),
            transitions=pt.DiscreteJumpTransition(domain=domain,
                                                  p_stay=0.7),
            sampler=pt.SingleCoreSampler(),
        )
        abc.new("sqlite://", {"y": true_k})
        h = abc.run(max_nr_populations=4)
        df, w = h.get_distribution(0, h.max_t)
        ks = df["k"].to_numpy()
        assert set(np.unique(ks)) <= set(float(v) for v in domain)
        # >50% of normalized weight on the true grid point makes it the
        # weighted posterior mode
        p_true = float(w[ks == true_k].sum())
        assert p_true > 0.5, p_true
