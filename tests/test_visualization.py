"""Visualization smoke tests: every plot runs against a tiny History.

Mirrors reference test/visualization/test_visualization.py (no-crash + axes
invariants, Agg backend).
"""
import matplotlib

matplotlib.use("Agg")

import jax
import numpy as np
import pytest

import pyabc_tpu as pt
import pyabc_tpu.visualization as viz


@pytest.fixture(scope="module")
def history():
    @pt.JaxModel.from_function(["a", "b"], name="toy")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        return {
            "x": theta[0] + 0.3 * jax.random.normal(k1),
            "y": theta[1] + 0.3 * jax.random.normal(k2),
        }

    prior = pt.Distribution(a=pt.RV("norm", 0.0, 1.0),
                            b=pt.RV("uniform", -2.0, 4.0))
    abc = pt.ABCSMC(model, prior, pt.AdaptivePNormDistance(p=2),
                    population_size=60, seed=0)
    abc.new("sqlite://", {"x": 0.5, "y": 0.5})
    h = abc.run(max_nr_populations=3)
    h._distance = abc.distance_function
    return h


def test_kde_1d(history):
    ax = viz.plot_kde_1d_highlevel(history, "a", refval={"a": 0.5})
    assert ax.get_xlabel() == "a"


def test_kde_2d(history):
    ax = viz.plot_kde_2d_highlevel(history, "a", "b")
    assert ax.get_xlabel() == "a" and ax.get_ylabel() == "b"


def test_kde_matrix(history):
    axes = viz.plot_kde_matrix_highlevel(history)
    assert len(axes) == 2


def test_histograms(history):
    viz.plot_histogram_1d(history, "a")
    viz.plot_histogram_2d(history, "a", "b")
    axes = viz.plot_histogram_matrix(history)
    assert len(axes) == 2


def test_epsilons(history):
    ax = viz.plot_epsilons(history)
    assert "epsilon" in ax.get_ylabel()


def test_sample_numbers(history):
    viz.plot_sample_numbers(history)
    ax = viz.plot_sample_numbers_trajectory(history)
    assert ax.get_ylabel() == "simulations"


def test_acceptance_rates(history):
    ax = viz.plot_acceptance_rates_trajectory(history)
    assert ax.get_ylabel() == "acceptance rate"


def test_model_probabilities(history):
    ax = viz.plot_model_probabilities(history)
    assert ax.get_ylabel() == "model probability"


def test_effective_sample_sizes(history):
    viz.plot_effective_sample_sizes(history, relative=True)


def test_walltimes(history):
    viz.plot_total_walltime(history)
    viz.plot_walltime(history)
    ax = viz.plot_eps_walltime(history, unit="m")
    assert ax.get_xlabel() == "cumulative walltime [m]"


def test_credible_intervals(history):
    axes = viz.plot_credible_intervals(history, levels=(0.5, 0.95))
    assert len(axes) == 2
    viz.plot_credible_intervals_for_time([history], t=history.max_t)


def test_distance_weights(history):
    ax = viz.plot_distance_weights(history._distance)
    assert ax.get_ylabel() == "weight"


def test_plot_sensitivity_sankey():
    """Sensitivity flow plot from a fitted LinearPredictor and from a raw
    matrix (reference plot_sensitivity_sankey, matplotlib-rendered)."""
    import numpy as np

    from pyabc_tpu.predictor import LinearPredictor
    from pyabc_tpu.visualization import plot_sensitivity_sankey

    rng = np.random.default_rng(0)
    x = rng.normal(size=(200, 5))
    y = np.stack([2 * x[:, 0] + x[:, 3], -x[:, 1]], axis=1)
    pred = LinearPredictor()
    pred.fit(x, y)
    ax = plot_sensitivity_sankey(
        pred, sumstat_labels=list("abcde"), par_labels=["p", "q"]
    )
    assert ax is not None
    # raw-matrix input
    ax2 = plot_sensitivity_sankey(np.abs(rng.normal(size=(4, 3))))
    assert ax2 is not None
    import pytest

    with pytest.raises(ValueError, match="all zeros"):
        plot_sensitivity_sankey(np.zeros((3, 2)))


def test_plot_sensitivity_sankey_errors():
    import numpy as np
    import pytest

    from pyabc_tpu.predictor import LinearPredictor
    from pyabc_tpu.visualization import plot_sensitivity_sankey

    with pytest.raises(ValueError, match="no linear sensitivity"):
        plot_sensitivity_sankey(LinearPredictor())  # unfitted
    with pytest.raises(ValueError, match="must be 2-d"):
        plot_sensitivity_sankey(np.ones(4))


def test_plot_data_default_and_callback():
    """plot_data_default / plot_data_callback (reference
    pyabc/visualization/data.py): observed-vs-simulated panels for vector
    and scalar statistics, plus the user-callback variant."""
    from pyabc_tpu.visualization import plot_data_callback, plot_data_default

    obs = {"traj": np.sin(np.linspace(0, 1, 20)), "peak": 0.9}
    sims = [{"traj": np.cos(np.linspace(0, 1, 20)), "peak": 0.7},
            {"traj": np.zeros(20), "peak": 1.1}]
    axes = plot_data_default(obs, sims)
    assert len(axes) == 2
    axes1 = plot_data_default(obs, sims[0], keys=["traj"])
    assert len(axes1) == 1

    seen = []

    def f_plot(key, y0, ys, ax):
        seen.append((key, len(ys)))
        ax.plot(y0)

    agg = []

    def f_agg(o, s, ax):
        agg.append(True)

    axes2 = plot_data_callback(obs, sims, f_plot, f_plot_aggregated=f_agg)
    assert len(axes2) == 3
    assert ("traj", 2) in seen and ("peak", 2) in seen
    assert agg == [True]
    import matplotlib.pyplot as plt

    plt.close("all")
