"""Scenario-zoo model coverage (ISSUE 15 satellites).

``models/{gillespie,sir,ode,model_selection}.py`` were shipped untested;
this file anchors them: host-oracle parity for the tau-leap engine
(plain and midpoint — a python-loop oracle consuming the identical key
stream must reproduce the scanned kernel bit-exactly), RK4/SIR oracle
parity, network-SIR conservation, and a K>1 model-selection fused run
asserting per-model posterior masses against the closed form.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import gillespie as g
from pyabc_tpu.models import model_selection as msel
from pyabc_tpu.models import sir
from pyabc_tpu.models.ode import rk4_at_times


# ------------------------------------------------------------- tau-leap

def _tau_leap_oracle(key, x0, stoich, prop, t1, n_leaps, save_every=1,
                     midpoint=False):
    """Python-loop twin of models.gillespie.tau_leap: same keys, same
    per-leap math, no lax.scan — the host oracle the kernel must match
    bit-exactly."""
    tau = t1 / n_leaps
    stoich = np.asarray(stoich, np.float32)
    keys = jax.random.split(key, n_leaps)
    x = np.asarray(x0, np.float32)
    traj = []
    for i in range(n_leaps):
        a = np.maximum(np.asarray(prop(jnp.asarray(x))), 0.0)
        if midpoint:
            x_mid = np.maximum(x + 0.5 * tau * a @ stoich, 0.0)
            a = np.maximum(np.asarray(prop(jnp.asarray(x_mid))), 0.0)
        n_fire = np.asarray(
            jax.random.poisson(keys[i], jnp.asarray(a * tau))
        ).astype(np.float32)
        x = np.maximum(x + n_fire @ stoich, 0.0)
        traj.append(x.copy())
    traj = np.stack(traj)
    if save_every > 1:
        traj = traj[save_every - 1::save_every]
    return traj


@pytest.mark.parametrize("midpoint", [False, True])
def test_tau_leap_host_oracle_parity(midpoint):
    stoich = jnp.asarray([[1.0], [-1.0]])

    def prop(x):
        return jnp.stack([jnp.asarray(10.0), 0.3 * x[0]])

    key = jax.random.key(5)
    kern = np.asarray(g.tau_leap(key, jnp.asarray([40.0]), stoich, prop,
                                 10.0, 50, save_every=5,
                                 midpoint=midpoint))
    oracle = _tau_leap_oracle(key, [40.0], [[1.0], [-1.0]], prop, 10.0,
                              50, save_every=5, midpoint=midpoint)
    assert np.array_equal(kern, oracle)


def test_tau_leap_grid_validation():
    stoich = jnp.asarray([[1.0], [-1.0]])

    def prop(x):
        return jnp.stack([jnp.asarray(1.0), x[0]])

    with pytest.raises(ValueError, match="save_every"):
        g.tau_leap(jax.random.key(0), jnp.asarray([1.0]), stoich, prop,
                   1.0, 10, save_every=3)
    with pytest.raises(ValueError, match="n_obs"):
        g.make_birth_death_model(n_leaps=200, n_obs=21)
    with pytest.raises(ValueError, match="segments"):
        g.make_birth_death_model(n_leaps=200, n_obs=20, segments=3)
    with pytest.raises(ValueError, match="segments"):
        g.make_stochastic_lv_model(n_leaps=300, n_obs=20, segments=8)


def test_midpoint_segmented_chain_matches_full():
    m = g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5,
                                 midpoint=True)
    spec = m.sumstat_spec()
    from pyabc_tpu.ops.segment import index_map_for

    imap = index_map_for(m.segmented, spec)
    key, theta = jax.random.key(9), jnp.asarray([1.0, -0.5])
    full = np.asarray(spec.flatten(m.sim(key, theta)))
    carry = m.segmented.init(key, theta)
    buf = np.zeros(spec.total_size, np.float32)
    for j in range(m.segmented.n_segments):
        carry, vals = m.segmented.step(carry, jnp.asarray(j, jnp.int32))
        buf[imap[j]] = np.asarray(vals)
    assert np.array_equal(buf, full)


# ------------------------------------------------------------------ SIR

def test_sir_rk4_host_oracle_parity():
    """rk4_at_times vs a python-loop RK4 on the SIR right-hand side."""
    from pyabc_tpu.models.sir import _sir_rhs, Y0

    ts = np.linspace(0.0, 30.0, 7)
    beta, gamma = 0.4, 0.1
    traj = np.asarray(rk4_at_times(_sir_rhs, jnp.asarray(Y0), ts, 4,
                                   args=(beta, gamma)))
    y = np.asarray(Y0, np.float32)
    dt = np.float32((ts[1] - ts[0]) / 4)
    oracle = [y.copy()]
    for _ in range(len(ts) - 1):
        for _ in range(4):
            f = lambda z: np.asarray(_sir_rhs(jnp.asarray(z), beta, gamma))
            k1 = f(y)
            k2 = f(y + np.float32(0.5) * dt * k1)
            k3 = f(y + np.float32(0.5) * dt * k2)
            k4 = f(y + dt * k3)
            y = y + (dt / np.float32(6.0)) * (k1 + 2 * k2 + 2 * k3 + k4)
        oracle.append(y.copy())
    assert np.allclose(traj, np.stack(oracle), rtol=1e-5, atol=1e-4)


def test_network_sir_conservation_and_spread():
    model = sir.make_network_sir_model(n_patches=6, n_obs=8, segments=4)
    spec = model.sumstat_spec()
    assert spec.total_size == 8 * 6  # large per-particle state
    out = model.sim(jax.random.key(0),
                    jnp.asarray([sir.TRUE_PARS["beta"],
                                 sir.TRUE_PARS["gamma"]]))
    inf = np.asarray(out["infected"]).reshape(8, 6)
    assert np.all(np.isfinite(inf)) and np.all(inf >= 0)
    # the epidemic must actually propagate beyond the seeded patch
    assert inf[-1, 3] > 0.01
    # compartment conservation: integrate the carry chain directly
    seg = model.segmented
    carry = seg.init(jax.random.key(0), jnp.asarray([0.4, 0.1]))
    for j in range(seg.n_segments):
        carry, _ = seg.step(carry, jnp.asarray(j, jnp.int32))
    totals = np.asarray(carry["y"]).sum(axis=0)
    assert np.allclose(totals, sir.N_POP, rtol=1e-3)


# ------------------------------------------------- K>1 model selection

def test_tractable_pair_fused_posterior_masses():
    """K=2 conjugate Gaussian pair through the fused kernel: posterior
    model probabilities against the closed form."""
    models, priors, analytic = msel.tractable_pair()
    x0 = 1.2
    abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                    population_size=400, eps=pt.MedianEpsilon(),
                    seed=4, fused_generations=4)
    abc.new("sqlite://", {"x": x0})
    h = abc.run(max_nr_populations=5)
    probs = h.get_model_probabilities(h.max_t)
    got = np.asarray([float(probs["p"].get(m_i, 0.0))
                      for m_i in range(2)])
    want = analytic(x0)
    # ABC posterior at finite epsilon: coarse but unambiguous ordering
    assert abs(got[0] - want[0]) < 0.25
    assert got[0] > got[1]


def test_ode_family_segmented_early_reject_smoke():
    """K=3 segmented ODE family through the early-reject fused kernel:
    completes, masses normalize, and lanes actually retire."""
    models, priors, _ts = msel.ode_family(segments=4)
    obs = msel.observed_ode_family(seed=0, true_model=1, segments=4)
    abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                    population_size=96, eps=pt.MedianEpsilon(),
                    seed=2, fused_generations=3, early_reject="auto")
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=3)
    probs = h.get_model_probabilities(h.max_t)
    assert abs(float(np.asarray(probs).sum()) - 1.0) < 1e-6
    retired = sum(
        (h.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h.max_t + 1)
    )
    assert retired >= 0  # accounting present (keys in telemetry)
    assert "retired_early" in (h.get_telemetry(h.max_t) or {})


def test_ode_family_segmented_matches_unsegmented_family_shapes():
    models_s, priors_s, ts_s = msel.ode_family(segments=4)
    models_u, priors_u, ts_u = msel.ode_family()
    assert [m.space.dim for m in models_s] == [
        m.space.dim for m in models_u]
    for m in models_s:
        out = m.sim(jax.random.key(0),
                    jnp.zeros((m.space.dim,), jnp.float32) + 0.4)
        assert np.asarray(out["y"]).shape == (12,)
