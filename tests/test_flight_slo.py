"""Round 22: flight recorder, SLO burn-rate engine, span federation.

Unit coverage for the observability tentpole — everything here runs on
VirtualClock / localhost sockets, no jax devices:

- crash-safe flight files: CRC-framed write/read round trip, a typed
  :class:`FlightCorruptError` per corruption mode, bounded rings,
  metric deltas, dump-never-raises;
- SLO engine: SLI-shape validation, multi-window burn-rate fire/clear
  on the injected clock, ``pyabc_tpu_slo_*`` gauge export, histogram-
  threshold SLI conservatism;
- Histogram satellites: lock-consistent ``snapshot()``, the shared
  log2-bucket ``quantile()``, tenant-labelled exposition and the
  ``+Inf`` cumulative invariant under concurrent observes;
- federation: sink/shipper round trip over TCP, offset correction via
  the PR-18 host-clock estimates, cursor dedup, best-effort death, and
  SyncLedger identity with federation on vs off.
"""
import re
import threading
import time

import pytest

from pyabc_tpu.observability import (
    MetricsRegistry,
    Tracer,
    VirtualClock,
    clear_federated_spans,
    federated_spans_snapshot,
    fire_span_ship_hooks,
    ingest_remote_spans,
    install_span_ship_hook,
    read_flight,
    record_host_clock_offset,
    render_timeline,
    uninstall_span_ship_hook,
    write_flight,
)
from pyabc_tpu.observability.metrics import Histogram, slo_metric
from pyabc_tpu.observability.recorder import (
    FlightCorruptError,
    FlightRecorder,
)
from pyabc_tpu.observability.slo import (
    FAST_BURN_THRESHOLD,
    SLO,
    SloEngine,
    default_slos,
)


@pytest.fixture(autouse=True)
def _clean_federation():
    clear_federated_spans()
    yield
    clear_federated_spans()


# ====================================================== flight files
def test_flight_write_read_round_trip(tmp_path):
    path = str(tmp_path / "t.flight")
    payload = {"run_id": "t1", "entries": [{"kind": "x", "ts": 1.0}],
               "nested": {"a": [1, 2, 3]}}
    n = write_flight(path, payload)
    assert n > 0
    assert read_flight(path) == payload


def test_flight_corruption_raises_typed_errors(tmp_path):
    path = str(tmp_path / "t.flight")
    write_flight(path, {"run_id": "t1"})
    good = (tmp_path / "t.flight").read_bytes()

    def corrupt(data, name):
        p = tmp_path / name
        p.write_bytes(data)
        with pytest.raises(FlightCorruptError) as ei:
            read_flight(str(p))
        return str(ei.value)

    # each validation step produces its own reason, in order
    assert "truncated header" in corrupt(good[:8], "short.flight")
    assert "magic" in corrupt(b"XXXX" + good[4:], "magic.flight")
    bad_ver = good[:4] + (99).to_bytes(4, "little") + good[8:]
    assert "version" in corrupt(bad_ver, "ver.flight")
    assert corrupt(good[:-4], "trunc.flight")  # short payload
    flipped = good[:-1] + bytes([good[-1] ^ 0xFF])
    assert "crc" in corrupt(flipped, "crc.flight").lower()


def test_recorder_ring_bounds_and_drop_count():
    clk = VirtualClock()
    rec = FlightRecorder("t1", clock=clk, max_entries=4)
    for i in range(10):
        clk.advance(1.0)
        rec.note("tick", i=i)
    snap = rec.snapshot()
    assert len(snap["entries"]) == 4
    assert snap["entries_dropped"] == 6
    assert [e["i"] for e in snap["entries"]] == [6, 7, 8, 9]


def test_recorder_metric_deltas_since_arm():
    clk = VirtualClock()
    reg = MetricsRegistry(clock=clk)
    reg.counter("c_total", "x").inc(5)
    rec = FlightRecorder("t1", clock=clk)
    rec.arm(metrics=reg)
    reg.counter("c_total").inc(3)
    snap = rec.snapshot()
    assert snap["metrics"]["deltas"]["c_total"] == 3.0


def test_recorder_dump_never_raises(tmp_path):
    clk = VirtualClock()
    rec = FlightRecorder("t1", clock=clk,
                         path=str(tmp_path / "no" / "such" / "dir" / "f"))
    rec.note("x")
    assert rec.dump() is None  # unwritable path: logged, not raised
    assert rec.n_dumps == 0
    ok = rec.dump(path=str(tmp_path / "ok.flight"))
    assert ok is not None and rec.n_dumps == 1
    assert read_flight(ok)["run_id"] == "t1"


def test_recorder_snapshot_spans_and_timeline():
    clk = VirtualClock()
    tracer = Tracer(clock=clk)
    rec = FlightRecorder("t1", clock=clk)
    rec.arm(tracer=tracer)
    with tracer.span("work", gen=3):
        clk.advance(0.5)
    rec.note("fault", reason="test")
    snap = rec.snapshot(reason="unit")
    assert snap["reason"] == "unit"
    assert [s["name"] for s in snap["spans"]] == ["work"]
    text = render_timeline(snap)
    assert "work" in text and "fault" in text and "t1" in text


def test_timeline_merges_federated_spans_without_duplicates():
    clk = VirtualClock()
    clk.advance(100.0)
    tracer = Tracer(clock=clk)
    record_host_clock_offset("hostB", {"offset_s": 0.5,
                                       "uncertainty_s": 0.001})
    ingest_remote_spans("hostB", 1, [
        {"name": "remote_work", "start": 100.5, "end": 101.5,
         "thread": "MainThread", "attrs": {}}], tracer=tracer)
    rec = FlightRecorder("t1", clock=clk)
    rec.arm(tracer=tracer)
    snap = rec.snapshot()
    # the federated span rides ONLY the federated block — the tracer
    # mirror (thread host:1) is filtered from the local tail
    assert snap["spans"] == []
    assert len(snap["federated_spans"]) == 1
    fed = snap["federated_spans"][0]
    assert fed["thread"] == "host:1"
    assert fed["start"] == pytest.approx(100.0)  # offset-corrected
    text = render_timeline(snap)
    assert text.count("remote_work") == 1
    assert "hostB" in text  # host-clock table row


# ========================================================== SLO engine
def test_slo_validation_rejects_bad_shapes():
    with pytest.raises(ValueError):
        SLO(name="x", objective=1.5, good_counter="g", total_counter="t")
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.9)  # no SLI shape
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.9, histogram="h")  # no threshold
    with pytest.raises(ValueError):
        SLO(name="x", objective=0.9, good_counter="g",
            total_counter="t", bad_counter="b")  # two ratio shapes
    slo = SLO(name="x", objective=0.99, good_counter="g", bad_counter="b")
    assert slo.budget == pytest.approx(0.01)


def test_default_slos_cover_the_fleet_objectives():
    names = {s.name for s in default_slos()}
    assert names == {"admission_latency", "admission_availability",
                     "availability", "time_to_posterior", "retry_honesty"}


def _ratio_engine(clk, objective=0.99):
    reg = MetricsRegistry(clock=clk)
    slo = SLO(name="avail", objective=objective,
              good_counter="good_total", bad_counter="bad_total")
    eng = SloEngine(reg, slos=[slo], clock=clk,
                    sample_interval_s=10.0, register=False)
    return reg, eng


def test_burn_rate_alert_fires_under_overload_and_clears_on_drain():
    clk = VirtualClock()
    clk.advance(1.0)
    reg, eng = _ratio_engine(clk)
    good = reg.counter("good_total", "g")
    bad = reg.counter("bad_total", "b")
    eng.sample(force=True)  # baseline
    assert not eng.alerting("avail")

    # overload: 100% failures for 10 minutes — burns the 1% budget at
    # 100x on BOTH fast windows, far past the 14.4x page threshold
    for _ in range(60):
        clk.advance(10.0)
        bad.inc(5)
        eng.sample()
    ev = eng.evaluate("avail")
    assert ev["burn_fast"] > FAST_BURN_THRESHOLD
    assert ev["alerting_fast"] and ev["alerting"]
    assert eng.alerting("avail") and eng.alerting()

    # drain: goods only until both fast windows (5m, 1h) roll past the
    # bad stretch — the PAGE clears (the slow-ticket pair may keep
    # burning: that budget was genuinely spent)
    for _ in range(400):
        clk.advance(10.0)
        good.inc(5)
        eng.sample()
    ev = eng.evaluate("avail")
    assert not ev["alerting_fast"], ev
    # ... and once the slow 6h/3d windows roll past the outage too,
    # the SLO is fully quiet again
    for _ in range(320):
        clk.advance(900.0)
        good.inc(5)
        eng.sample(force=True)
    assert not eng.alerting("avail")


def test_transient_spike_on_short_window_alone_does_not_page():
    clk = VirtualClock()
    clk.advance(1.0)
    reg, eng = _ratio_engine(clk)
    good = reg.counter("good_total", "g")
    bad = reg.counter("bad_total", "b")
    # a long healthy stretch fills the 1h window with goods
    for _ in range(360):
        clk.advance(10.0)
        good.inc(100)
        eng.sample()
    # then one bad one-minute blip: the 5m window burns hot, the 1h
    # window does not — the multi-window rule holds the page
    for _ in range(6):
        clk.advance(10.0)
        bad.inc(300)
        eng.sample()
    ev = eng.evaluate("avail")
    assert ev["burn"]["300s"] > FAST_BURN_THRESHOLD
    assert ev["burn_fast"] <= FAST_BURN_THRESHOLD
    assert not ev["alerting"]


def test_slo_gauges_exported_on_sample():
    clk = VirtualClock()
    clk.advance(1.0)
    reg, eng = _ratio_engine(clk)
    reg.counter("bad_total", "b").inc(10)
    eng.sample(force=True)
    clk.advance(10.0)
    reg.counter("bad_total").inc(10)
    eng.sample(force=True)
    snap = reg.snapshot()
    assert slo_metric("avail", "burn_fast") in snap
    assert snap[slo_metric("avail", "alerting")] == 1.0
    assert snap[slo_metric("avail", "bad_fraction")] == 1.0


def test_histogram_threshold_sli_is_conservative():
    clk = VirtualClock()
    reg = MetricsRegistry(clock=clk)
    h = reg.histogram("lat_seconds", "x")
    for _ in range(8):
        h.observe(0.001)  # well under threshold
    h.observe(50.0)       # well over
    slo = SLO(name="lat", objective=0.5, histogram="lat_seconds",
              threshold=1.0)
    eng = SloEngine(reg, slos=[slo], clock=clk, register=False)
    good, total = eng._measure(slo)
    assert total == 9.0
    # conservative: good counts only buckets whose UPPER edge is at or
    # under the threshold, so 8 <= good < 9 and the straddler is bad
    assert 8.0 <= good < 9.0


def test_slo_sample_throttles_on_interval():
    clk = VirtualClock()
    clk.advance(1.0)
    _, eng = _ratio_engine(clk)
    assert eng.sample() is True
    assert eng.sample() is False       # same instant: throttled
    clk.advance(5.0)
    assert eng.sample() is False       # < interval
    assert eng.sample(force=True) is True
    clk.advance(10.0)
    assert eng.sample() is True


# ================================================ Histogram satellites
def test_histogram_snapshot_is_self_consistent():
    h = Histogram("h", "x")
    for v in (0.001, 0.02, 0.3, 4.0):
        h.observe(v)
    snap = h.snapshot()
    assert sum(snap["buckets"]) == snap["count"] == 4
    assert snap["min"] == 0.001 and snap["max"] == 4.0
    assert snap["sum"] == pytest.approx(4.321)


def test_histogram_quantile_semantics():
    h = Histogram("h", "x")
    assert h.quantile(0.5) != h.quantile(0.5)  # NaN when empty
    for _ in range(90):
        h.observe(0.001)
    for _ in range(10):
        h.observe(5.0)
    # p50 lands in the fast bucket (upper edge capped at observed max);
    # p99 lands in the slow bucket
    assert h.quantile(0.5) < 0.01
    assert 4.0 < h.quantile(0.99) <= 8.2
    assert h.quantile(1.0) <= h.max
    # overflow values resolve to the observed max, not an edge
    h2 = Histogram("h2", "x")
    h2.observe(1e12)
    assert h2.quantile(0.9) == 1e12


def test_histogram_summary_has_shared_percentiles():
    h = Histogram("h", "x")
    s = h.summary()
    assert s["p50"] is None and s["p99"] is None
    for _ in range(100):
        h.observe(0.01)
    s = h.summary()
    assert s["p50"] == pytest.approx(h.quantile(0.5))
    assert s["p90"] == pytest.approx(h.quantile(0.9))
    assert s["p99"] == pytest.approx(h.quantile(0.99))


def _parse_prom_hist(text, name, label=None):
    """{le_value: cumulative_count} + count/sum for one exposition."""
    buckets, count = {}, None
    for line in text.splitlines():
        if line.startswith(f"{name}_bucket"):
            if label is not None and label not in line:
                continue
            le = re.search(r'le="([^"]+)"', line).group(1)
            buckets[le] = float(line.rsplit(" ", 1)[1])
        elif line.startswith(f"{name}_count"):
            count = float(line.rsplit(" ", 1)[1])
    return buckets, count


def test_prometheus_text_tenant_labelled_histogram():
    from pyabc_tpu.observability.export import prometheus_text

    reg = MetricsRegistry()
    h = reg.histogram("work_seconds", "x")
    for v in (0.001, 0.01, 99.0):
        h.observe(v)
    text = prometheus_text(reg, labels={"tenant": "t-9"})
    assert 'tenant="t-9"' in text
    buckets, count = _parse_prom_hist(text, "work_seconds",
                                      label='tenant="t-9"')
    assert count == 3.0 and buckets["+Inf"] == 3.0
    # cumulative: monotone nondecreasing in le order, +Inf == count
    ordered = [buckets[k] for k in buckets if k != "+Inf"]
    assert ordered == sorted(ordered)
    # every bucket line carries BOTH labels
    for line in text.splitlines():
        if line.startswith("work_seconds_bucket"):
            assert 'le="' in line and 'tenant="t-9"' in line


def test_prometheus_histogram_inf_invariant_under_concurrent_observes():
    """The satellite-1 fix: exposition reads one locked snapshot, so
    within a single scrape +Inf ALWAYS equals _count even while other
    threads observe concurrently (the old unlocked read could catch
    the buckets and the count mid-update)."""
    from pyabc_tpu.observability.export import prometheus_text

    reg = MetricsRegistry()
    h = reg.histogram("busy_seconds", "x")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            h.observe(0.001 * (1 + i % 7))
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            buckets, count = _parse_prom_hist(
                prometheus_text(reg), "busy_seconds")
            assert buckets["+Inf"] == count
            assert sum(b for k, b in buckets.items()
                       if k == "+Inf") == count
    finally:
        stop.set()
        for t in threads:
            t.join()


# ========================================================== federation
def test_ingest_remote_spans_offset_correction():
    tracer = Tracer(clock=VirtualClock())
    record_host_clock_offset("fed-h1", {"offset_s": 2.0,
                                        "uncertainty_s": 0.01})
    n = ingest_remote_spans("fed-h1", 3, [
        {"name": "gen", "start": 12.0, "end": 13.0,
         "thread": "MainThread", "attrs": {"g": 1}}], tracer=tracer)
    assert n == 1
    [sp] = federated_spans_snapshot()
    assert sp["thread"] == "host:3"
    assert sp["start"] == pytest.approx(10.0)  # local = remote - offset
    assert sp["end"] == pytest.approx(11.0)
    assert sp["attrs"]["origin_host"] == "fed-h1"
    assert sp["attrs"]["origin_thread"] == "MainThread"
    # mirrored onto the local tracer under the host pseudo-thread
    assert [s.thread for s in tracer.spans()] == ["host:3"]


def test_ingest_without_clock_estimate_is_flagged_uncorrected():
    n = ingest_remote_spans("never-measured-host", 7, [
        {"name": "gen", "start": 5.0, "end": 6.0, "attrs": {}}])
    assert n == 1
    [sp] = federated_spans_snapshot()
    assert sp["start"] == 5.0  # passed through untouched
    assert sp["attrs"]["offset_corrected"] is False


def test_span_sink_and_shipper_round_trip():
    from pyabc_tpu.parallel.distributed import SpanShipper, serve_span_sink

    clk = VirtualClock()
    local = Tracer(clock=clk)   # primary-side merge target
    remote = Tracer(clock=clk)  # the "other host"'s tracer
    batches = []
    port, stop = serve_span_sink(tracer=local,
                                 on_batch=lambda b: batches.append(b))
    try:
        with remote.span("remote_gen", gen=1):
            clk.advance(1.0)
        with remote.span("remote_gen", gen=2):
            clk.advance(1.0)
        shipper = SpanShipper(f"127.0.0.1:{port}", host="hB",
                              process_id=1, tracer=remote)
        assert shipper.ship() == 2
        assert shipper.ship() == 0  # cursor: nothing new, no resend
        with remote.span("remote_gen", gen=3):
            clk.advance(1.0)
        assert shipper.ship() == 1
        shipper.close()
        # ship() returns at socket-write time; ingestion happens on the
        # sink's reader thread — wait for it to drain before asserting
        deadline = time.monotonic() + 10.0
        while len(batches) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        stop()
    assert len(batches) == 2
    fed = federated_spans_snapshot()
    assert len(fed) == 3 and all(s["thread"] == "host:1" for s in fed)
    assert sorted(s["attrs"]["gen"] for s in fed) == [1, 2, 3]
    # merged into the primary's tracer for the flight recorder
    assert len([s for s in local.spans() if s.thread == "host:1"]) == 3


def test_shipper_skips_already_federated_spans():
    """A primary that is ALSO a shipper (mid-tier fan-in) must not
    re-ship spans it ingested from other hosts — host:* threads are
    excluded from the cursor scan."""
    from pyabc_tpu.parallel.distributed import SpanShipper, serve_span_sink

    clk = VirtualClock()
    mid = Tracer(clock=clk)
    ingest_remote_spans("leaf", 5, [
        {"name": "leaf_gen", "start": 1.0, "end": 2.0, "attrs": {}}],
        tracer=mid)
    sink_tr = Tracer(clock=clk)
    port, stop = serve_span_sink(tracer=sink_tr)
    try:
        shipper = SpanShipper(f"127.0.0.1:{port}", host="mid",
                              process_id=1, tracer=mid)
        assert shipper.ship() == 0  # the host:5 mirror is not re-shipped
        shipper.close()
    finally:
        stop()


def test_shipper_is_best_effort_after_sink_death():
    from pyabc_tpu.parallel.distributed import SpanShipper, serve_span_sink

    clk = VirtualClock()
    remote = Tracer(clock=clk)
    port, stop = serve_span_sink()
    stop()  # sink is gone before the first ship
    shipper = SpanShipper(f"127.0.0.1:{port}", host="hB", process_id=1,
                          tracer=remote)
    with remote.span("gen"):
        clk.advance(1.0)
    assert shipper.ship() == 0  # no raise: telemetry never kills a run
    assert shipper.ship() == 0
    shipper.close()


def test_ship_hooks_fire_and_self_heal():
    calls = []

    def good_hook():
        calls.append("good")

    def bad_hook():
        calls.append("bad")
        raise OSError("sink died")

    install_span_ship_hook(good_hook)
    install_span_ship_hook(bad_hook)
    try:
        fire_span_ship_hooks()
        fire_span_ship_hooks()
        # the raising hook uninstalled itself after the first firing
        assert calls == ["good", "bad", "good"]
    finally:
        uninstall_span_ship_hook(good_hook)
        uninstall_span_ship_hook(bad_hook)


def test_federation_adds_zero_blocking_syncs():
    """THE federation contract: a fused run with a SpanShipper firing
    on every chunk books a SyncLedger IDENTICAL to the same run with
    federation off — shipping is pure host-side TCP."""
    import jax

    import pyabc_tpu as pt
    from pyabc_tpu.parallel.distributed import SpanShipper, serve_span_sink

    def run_once(with_federation):
        @pt.JaxModel.from_function(["theta"], name="gauss")
        def model(key, theta):
            return {"x": theta[0] + 0.5 * jax.random.normal(key)}

        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=64, eps=pt.MedianEpsilon(),
                        seed=11, fused_generations=2)
        abc.new("sqlite://", {"x": 1.0}, store_sum_stats=False)
        shipper = stop = None
        if with_federation:
            port, stop = serve_span_sink()
            side = Tracer()  # spans to ship on every chunk hook firing
            with side.span("pre_run"):
                pass
            shipper = SpanShipper(f"127.0.0.1:{port}", host="self",
                                  process_id=0, tracer=side)
            shipper.install()
        try:
            abc.run(max_nr_populations=4)
        finally:
            if shipper is not None:
                shipper.close()
            if stop is not None:
                stop()
        return dict(abc.sync_ledger.by_kind()), abc.sync_ledger.count

    kinds_off, count_off = run_once(False)
    kinds_on, count_on = run_once(True)
    assert kinds_on == kinds_off
    assert count_on == count_off


# ================================================================ CLI
def test_manager_postmortem_renders_flight_file(tmp_path):
    from click.testing import CliRunner

    from pyabc_tpu.cli import manager_cmd

    clk = VirtualClock()
    rec = FlightRecorder("t-pm", clock=clk)
    rec.note("fault", reason="unit")
    path = rec.dump(path=str(tmp_path / "t.flight"))
    res = CliRunner().invoke(manager_cmd, ["--postmortem", path])
    assert res.exit_code == 0, res.output
    assert "t-pm" in res.output and "fault" in res.output


def test_manager_requires_host_port_without_postmortem():
    from click.testing import CliRunner

    from pyabc_tpu.cli import manager_cmd

    res = CliRunner().invoke(manager_cmd, [])
    assert res.exit_code != 0
    assert "HOST and PORT" in res.output
