"""Test configuration: force a virtual 8-device CPU platform BEFORE jax import.

Mirrors the reference's test strategy (SURVEY.md §4): multi-node is tested as
multi-process-on-localhost there; here multi-chip is tested as a virtual 8-device
CPU mesh via --xla_force_host_platform_device_count.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# CI fallback leg (round 17): PYABC_TPU_BLOCK_PYARROW=1 makes pyarrow
# unimportable for the whole test process, proving the default row store
# and every optional-integration gate stay green without it. Installed
# BEFORE jax/pandas imports so nothing can cache a pyarrow module first.
if os.environ.get("PYABC_TPU_BLOCK_PYARROW") == "1":
    import importlib.abc
    import sys

    class _PyarrowBlocker(importlib.abc.MetaPathFinder):
        def find_spec(self, name, path=None, target=None):
            if name == "pyarrow" or name.startswith("pyarrow."):
                raise ImportError(
                    f"{name} import blocked (PYABC_TPU_BLOCK_PYARROW=1)")
            return None

    for _m in [m for m in sys.modules if m.split(".")[0] == "pyarrow"]:
        del sys.modules[_m]
    sys.meta_path.insert(0, _PyarrowBlocker())

# CI sumstat degradation leg (ISSUE 20): PYABC_TPU_BLOCK_SKLEARN=1
# makes sklearn AND optax unimportable, proving the learned-summary
# stack depends on neither for the LINEAR device path — the predictors
# are hand-rolled numpy/JAX, the in-kernel ridge fit is pure JAX, and
# optax is an optional dependency of the HOST MLP fit only.
if os.environ.get("PYABC_TPU_BLOCK_SKLEARN") == "1":
    import importlib.abc
    import sys

    class _LearnDepsBlocker(importlib.abc.MetaPathFinder):
        _roots = ("sklearn", "optax")

        def find_spec(self, name, path=None, target=None):
            if name.split(".")[0] in self._roots:
                raise ImportError(
                    f"{name} import blocked (PYABC_TPU_BLOCK_SKLEARN=1)")
            return None

    for _m in [m for m in sys.modules
               if m.split(".")[0] in ("sklearn", "optax")]:
        del sys.modules[_m]
    sys.meta_path.insert(0, _LearnDepsBlocker())

import jax
import numpy as np
import pytest

# Under axon the TPU tunnel ignores JAX_PLATFORMS; pin the default device to
# the (virtual 8-way) CPU platform so tests compile locally and fast.
try:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
except RuntimeError:  # pragma: no cover - no cpu platform registered
    pass

# Persistent compilation cache: the fused multi-generation programs cost
# ~15-23 s of XLA compile each on CPU; cache them across test runs so the
# suite pays that tax once per machine, not once per run. Set via the env
# var (not jax.config) so subprocess-based tests (examples, graft-entry
# dryrun, multihost workers) inherit it.
from pyabc_tpu.utils.xla_cache import setup_xla_cache  # noqa: E402

setup_xla_cache(
    os.path.join(os.path.expanduser("~"), ".cache", "pyabc_tpu_xla_cache"),
    export_env=True,
)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def _pyarrow_available() -> bool:
    from pyabc_tpu.storage.columnar import has_pyarrow

    return has_pyarrow()


@pytest.fixture(params=[
    "sqlite",
    pytest.param("sqlite+columnar", marks=pytest.mark.skipif(
        not _pyarrow_available(),
        reason="columnar History store needs the optional pyarrow")),
])
def store_scheme(request):
    """Both History backends (round 17): tests taking this fixture run
    once against the row store and once against the columnar store —
    the durability contracts (resume, prune_from, checkpoint ordering,
    serving requeue) must hold identically on each."""
    return request.param


@pytest.fixture(autouse=True)
def _close_matplotlib_figures():
    """Close every figure a test leaves open.

    The plot helpers (``visualization/util.py::get_figure``) create
    figures on demand; tests that don't close them accumulate until
    matplotlib's >20-open-figures RuntimeWarning fires mid-suite (the
    round-5 figure-leak warning). Teardown-only and guarded on the
    module already being imported, so non-plot tests pay nothing."""
    yield
    import sys

    close = getattr(sys.modules.get("matplotlib.pyplot"), "close", None)
    if close is not None:
        close("all")


@pytest.fixture(autouse=True)
def _cpu_burner():
    """CI-style background load: PYABC_TPU_TEST_CPU_BURN=<n> spawns n
    busy-loop subprocesses for the duration of each test.

    Used to reproduce full-suite-load conditions for timing-sensitive
    concurrency tests in isolation (the round-5
    ``test_look_ahead_delayed_evaluation_adaptive_distance`` flake was
    load-dependent; BASELINE.md records the 20x verification under this
    fixture). Off by default — the fixture is a no-op unless the env
    var is set."""
    import subprocess
    import sys as _sys

    n = int(os.environ.get("PYABC_TPU_TEST_CPU_BURN", "0") or 0)
    if not n:
        yield
        return
    procs = [
        subprocess.Popen(
            [_sys.executable, "-c", "while True:\n    sum(range(10000))"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        for _ in range(n)
    ]
    try:
        yield
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)
