"""Test configuration: force a virtual 8-device CPU platform BEFORE jax import.

Mirrors the reference's test strategy (SURVEY.md §4): multi-node is tested as
multi-process-on-localhost there; here multi-chip is tested as a virtual 8-device
CPU mesh via --xla_force_host_platform_device_count.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax
import numpy as np
import pytest

# Under axon the TPU tunnel ignores JAX_PLATFORMS; pin the default device to
# the (virtual 8-way) CPU platform so tests compile locally and fast.
try:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
except RuntimeError:  # pragma: no cover - no cpu platform registered
    pass

# Persistent compilation cache: the fused multi-generation programs cost
# ~15-23 s of XLA compile each on CPU; cache them across test runs so the
# suite pays that tax once per machine, not once per run. Set via the env
# var (not jax.config) so subprocess-based tests (examples, graft-entry
# dryrun, multihost workers) inherit it.
from pyabc_tpu.utils.xla_cache import setup_xla_cache  # noqa: E402

setup_xla_cache(
    os.path.join(os.path.expanduser("~"), ".cache", "pyabc_tpu_xla_cache"),
    export_env=True,
)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
