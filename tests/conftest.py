"""Test configuration: force a virtual 8-device CPU platform BEFORE jax import.

Mirrors the reference's test strategy (SURVEY.md §4): multi-node is tested as
multi-process-on-localhost there; here multi-chip is tested as a virtual 8-device
CPU mesh via --xla_force_host_platform_device_count.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
