"""Sharded fused sampling (ISSUE 9): the shard_map multigen kernel.

conftest forces ``--xla_force_host_platform_device_count=8``, so a real
8-device mesh exists and GSPMD/shard_map insert real cross-device
collectives — the same mechanism the CI ``mesh`` job and the bench
``mesh`` lane use.

The parity contract: the sharded reduction (per-shard lane-key blocks,
per-shard reservoirs and quotas) is a pure function of ``n_shards``, not
of the physical device count — ``ABCSMC(sharded=8)`` WITHOUT a mesh runs
the identical reduction vmapped over virtual shards on one device, and a
real 8-device mesh run must be bit-identical to it. Statistical
agreement with the plain single-device reduction is asserted separately
(different reductions of the same proposals, same posterior).
"""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import pyabc_tpu as pt
from pyabc_tpu.observability import MetricsRegistry

pytestmark = pytest.mark.mesh

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _mesh(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} virtual cpu devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), axis_names=("particles",))


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss_sharded")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _make(seed=21, pop=128, G=3, mesh=None, sharded=None, **kwargs):
    abc = pt.ABCSMC(
        _gauss_model(), pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.PNormDistance(p=2), population_size=pop,
        eps=pt.MedianEpsilon(), seed=seed, mesh=mesh, sharded=sharded,
        fused_generations=G, **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS})
    return abc


def _history_arrays(h):
    """Everything a bit-identity claim covers: epsilon trail plus every
    generation's (theta, weight, distance) arrays."""
    pops = h.get_all_populations().query("t >= 0")
    out = {"eps": pops["epsilon"].to_numpy()}
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        out[f"theta_{t}"] = df["theta"].to_numpy()
        out[f"w_{t}"] = np.asarray(w)
        out[f"d_{t}"] = h.get_weighted_distances(
            int(t))["distance"].to_numpy()
    return out


def _moments(h):
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
    return mu, sd


# ------------------------------------------------------------ parity

class TestShardedParity:
    def test_mesh_bit_identical_to_virtual_shards(self):
        """The lane-key reduction contract: an 8-device shard_map run and
        the SAME reduction vmapped over 8 virtual shards on one device
        produce bit-identical Histories — sharding is an execution
        choice, never a statistical one."""
        abc_v = _make(seed=21, sharded=8)
        assert abc_v._sharded_n() == 8
        h_v = abc_v.run(max_nr_populations=7)

        abc_m = _make(seed=21, mesh=_mesh())
        assert abc_m._sharded_n() == 8  # auto: mesh width
        h_m = abc_m.run(max_nr_populations=7)

        a, b = _history_arrays(h_m), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k], err_msg=f"mesh vs virtual shards diverged "
                                    f"at {k}")
        snap = abc_m._engine.snapshot()
        assert snap["mesh"]["devices"] == 8
        assert snap["mesh"]["imbalance"] >= 1.0
        assert len(snap["mesh"]["rounds_per_device"]) == 8

    @pytest.mark.parametrize("width", [1, 2, 4])
    def test_divisor_width_bit_identical_to_virtual_shards(self, width):
        """Round 15 width-independence: 8 shards on a NARROWER mesh
        (each device vmapping 8/width virtual shards inside the
        shard_map — the hybrid execution) stay bit-identical to the
        virtual-shard reference. This is the kernel contract the
        serving scheduler's re-place-on-any-width story stands on."""
        abc_v = _make(seed=23, sharded=8)
        h_v = abc_v.run(max_nr_populations=4)

        abc_h = _make(seed=23, mesh=_mesh(width), sharded=8)
        assert abc_h._sharded_n() == 8
        h_h = abc_h.run(max_nr_populations=4)

        a, b = _history_arrays(h_h), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=(f"width-{width} hybrid diverged from virtual "
                         f"shards at {k}"))

    def test_sharded_statistical_parity_with_single_device(self):
        """Different reductions of the same proposal stream: the sharded
        run must agree with the plain single-device run on the posterior
        (and both with the conjugate analytic answer)."""
        h_s = _make(seed=23).run(max_nr_populations=6)
        h_m = _make(seed=23, mesh=_mesh()).run(max_nr_populations=6)
        mu_s, sd_s = _moments(h_s)
        mu_m, sd_m = _moments(h_m)
        assert mu_m == pytest.approx(POST_MU, abs=0.25)
        assert mu_m == pytest.approx(mu_s, abs=0.2)
        assert sd_m == pytest.approx(sd_s, abs=0.15)

    def test_multimodel_sharded(self):
        """K>1 rides the sharded kernel: model ids travel with the
        gathered scalar columns, refits stay per-model masked."""
        from pyabc_tpu.models import model_selection as msel

        models, priors, analytic = msel.tractable_pair()
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=600, eps=pt.MedianEpsilon(),
                        seed=22, mesh=_mesh(), sharded=True,
                        fused_generations=3)
        assert abc._sharded_n() == 8
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        probs = h.get_model_probabilities(h.max_t)
        expected = analytic(X_OBS)
        for m in range(2):
            p = float(probs["p"].get(m, 0.0))
            assert p == pytest.approx(expected[m], abs=0.2), (m, p)


# ------------------------------------------------------- uneven shards

class TestUnevenShards:
    @pytest.mark.parametrize("pop", [300, 100])
    def test_population_not_divisible_by_mesh(self, pop):
        """pop % 8 != 0: leading shards take the remainder (static
        quotas), padding rows never leak — every persisted generation
        has exactly ``pop`` particles with positive total weight."""
        abc = _make(seed=31, pop=pop, mesh=_mesh(), sharded=True)
        h = abc.run(max_nr_populations=5)
        counts = h.get_nr_particles_per_population()
        for t in range(h.max_t + 1):
            assert counts[t] == pop, (t, counts[t])
            df, w = h.get_distribution(0, t)
            assert len(df) == pop
            w = np.asarray(w)
            assert np.all(np.isfinite(w)) and w.sum() == pytest.approx(1.0)
            assert np.all(np.isfinite(df["theta"].to_numpy()))
        mu, _ = _moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.3)

    def test_shard_quota_and_merge_index(self):
        from pyabc_tpu.ops.shard import merge_index, shard_quota_host

        q = shard_quota_host(300, 8)
        assert q.sum() == 300 and q.max() - q.min() <= 1
        idx = merge_index(300, 8, 64)
        assert len(idx) == 300
        # shard-blocked, dense within each shard
        assert idx[0] == 0 and idx[q[0]] == 64
        with pytest.raises(ValueError):
            merge_index(300, 8, 16)  # quota 38 > per-shard capacity 16


# ------------------------------------------------- sharding mechanics

class TestShardingMechanics:
    def test_outs_genuinely_sharded_and_merge_in_fetch(self):
        """The chunk outputs' row leaves live sharded across the 8
        devices (each holds its reservoir shard, not a replica); the
        packed fetch tree is the merged dense layout."""
        from pyabc_tpu.inference.dispatch import DispatchEngine

        captured = {}
        orig = DispatchEngine._fetch_tree

        def spy(self, res_i, t_at, g_lim):
            sh = res_i["outs"]["theta"].sharding
            captured.setdefault("spec", sh.spec if isinstance(
                sh, NamedSharding) else None)
            captured.setdefault(
                "shard_shapes",
                {s.data.shape
                 for s in res_i["outs"]["theta"].addressable_shards},
            )
            return orig(self, res_i, t_at, g_lim)

        DispatchEngine._fetch_tree = spy
        try:
            abc = _make(seed=41, pop=128, G=3, mesh=_mesh())
            h = abc.run(max_nr_populations=4)
        finally:
            DispatchEngine._fetch_tree = orig
        assert h.n_populations == 4
        # n_cap = 128 -> 16 rows per device; G=3 scan axis unsharded
        assert captured["spec"] == P(None, "particles")
        assert captured["shard_shapes"] == {(3, 16, 1)}

    def test_per_shard_rng_lanes_distinct(self):
        """Each shard proposes from its own lane-key block: a
        generation's accepted thetas contain no cross-shard duplicates
        (distinct PRNG lanes, not a replicated draw)."""
        abc = _make(seed=43, pop=128, mesh=_mesh())
        h = abc.run(max_nr_populations=3)
        df, _ = h.get_distribution(0, h.max_t)
        th = df["theta"].to_numpy()
        # merged layout is shard-blocked (16 rows per shard at pop 128):
        # no shard block may replicate another, and the accepted set is
        # overwhelmingly distinct (the f16 wire dtype may collapse a few
        # near-identical draws, so exact all-unique is too strict)
        blocks = th.reshape(8, 16)
        for i in range(8):
            for j in range(i + 1, 8):
                assert not np.array_equal(blocks[i], blocks[j]), (i, j)
        assert len(np.unique(th)) >= int(0.9 * len(th))


# ------------------------------------- engine invariants under sharding

class TestShardedEngine:
    def test_sync_budget_holds(self, monkeypatch):
        """The row merge rides the packed fetch: a sharded run pays the
        same syncs as an unsharded one — asserted STRICT (a budget
        violation raises instead of warning)."""
        monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
        abc = _make(seed=51, mesh=_mesh())
        abc.run(max_nr_populations=7)
        report = abc._engine.sync_budget_report()
        assert report["ok"], report
        assert report["syncs"] <= report["chunks"] + 8

    def test_speculative_rollback_bit_identical(self):
        """A stopping-rule hit with speculative sharded chunks in flight
        rolls them back unpersisted: History bit-identical to the
        depth-1 run of the same seed (rollback stays bit-identical per
        device — the carry chain and per-shard reservoirs never leak
        into the db)."""
        mesh = _mesh()
        probe = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=1)
        h_probe = probe.run(max_nr_populations=6)
        eps_trail = h_probe.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        assert len(eps_trail) >= 4
        min_eps = float(eps_trail[3])

        reg = MetricsRegistry()
        spec = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=4,
                     metrics=reg)
        spec.adopt_device_context(probe)
        h_spec = spec.run(minimum_epsilon=min_eps, max_nr_populations=12)
        assert spec._engine.speculative_rollbacks >= 1
        assert reg.snapshot()[
            "pyabc_tpu_speculative_rollbacks_total"] >= 1

        ref = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=1)
        ref.adopt_device_context(probe)
        h_ref = ref.run(minimum_epsilon=min_eps, max_nr_populations=12)

        a, b = _history_arrays(h_spec), _history_arrays(h_ref)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k], err_msg=f"sharded speculative run diverged "
                                    f"at {k}")
        assert h_spec.n_populations == h_ref.n_populations <= 6

    def test_health_poison_recovery_under_sharding(self):
        """The in-kernel health word still fires sharded (NaN flag is a
        cross-shard reduction) and recovery rolls back to a healthy
        carry: the poisoned run completes with the clean run's
        posterior."""
        from pyabc_tpu.resilience.faults import (
            FaultPlan,
            FaultRule,
            install_fault_plan,
            uninstall_fault_plan,
        )

        mesh = _mesh()
        clean = _make(seed=61, mesh=mesh)
        h_clean = clean.run(max_nr_populations=7)

        install_fault_plan(FaultPlan([
            FaultRule(site="device.carry", kind="nan_poison", after=1,
                      max_fires=1),
        ]))
        try:
            poisoned = _make(seed=61, mesh=mesh)
            poisoned.adopt_device_context(clean)
            h_p = poisoned.run(max_nr_populations=7)
        finally:
            uninstall_fault_plan()
        assert len(poisoned.health_supervisor.trail) >= 1
        a, b = _history_arrays(h_clean), _history_arrays(h_p)
        for k in a:
            np.testing.assert_allclose(
                a[k], b[k], rtol=1e-6, atol=1e-7,
                err_msg=f"poisoned sharded run diverged at {k}")


# ------------------------------------------------------------ gating

class TestShardedGating:
    def test_explicit_sharded_with_adaptive_distance_raises(self):
        abc = pt.ABCSMC(
            _gauss_model(),
            pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
            pt.AdaptivePNormDistance(p=2), population_size=128,
            eps=pt.MedianEpsilon(), seed=1, mesh=_mesh(), sharded=True,
            fused_generations=3,
        )
        with pytest.raises(ValueError, match="adaptive distances"):
            abc._sharded_n()

    def test_auto_mode_falls_back_for_adaptive_distance(self):
        abc = pt.ABCSMC(
            _gauss_model(),
            pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
            pt.AdaptivePNormDistance(p=2), population_size=128,
            eps=pt.MedianEpsilon(), seed=1, mesh=_mesh(),
            fused_generations=3,
        )
        assert abc._sharded_n() is None  # GSPMD path serves it instead

    def test_non_power_of_two_virtual_shards_raise(self):
        abc = _make(seed=1, sharded=3)
        with pytest.raises(ValueError, match="power of two"):
            abc._sharded_n()

    def test_mesh_width_must_divide_shard_count(self):
        # fewer shards than devices cannot spread over the mesh
        abc = _make(seed=1, mesh=_mesh(), sharded=4)
        with pytest.raises(ValueError, match="must divide"):
            abc._sharded_n()

    def test_divisor_width_mesh_runs_hybrid_shards(self):
        """Round 15 (mesh-aware serving): the mesh width only has to
        DIVIDE the shard count — each device vmaps its block of virtual
        shards, so an n-shard checkpoint re-places on any divisor-width
        sub-mesh."""
        assert _make(seed=1, mesh=_mesh(2), sharded=8)._sharded_n() == 8
        assert _make(seed=1, mesh=_mesh(4), sharded=8)._sharded_n() == 8
        # width == shards stays the plain per-device execution
        assert _make(seed=1, mesh=_mesh(8), sharded=8)._sharded_n() == 8
