"""Sharded fused sampling (ISSUE 9): the shard_map multigen kernel.

conftest forces ``--xla_force_host_platform_device_count=8``, so a real
8-device mesh exists and GSPMD/shard_map insert real cross-device
collectives — the same mechanism the CI ``mesh`` job and the bench
``mesh`` lane use.

The parity contract: the sharded reduction (per-shard lane-key blocks,
per-shard reservoirs and quotas) is a pure function of ``n_shards``, not
of the physical device count — ``ABCSMC(sharded=8)`` WITHOUT a mesh runs
the identical reduction vmapped over virtual shards on one device, and a
real 8-device mesh run must be bit-identical to it. Statistical
agreement with the plain single-device reduction is asserted separately
(different reductions of the same proposals, same posterior).
"""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import pyabc_tpu as pt
from pyabc_tpu.observability import MetricsRegistry

pytestmark = pytest.mark.mesh

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _mesh(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} virtual cpu devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), axis_names=("particles",))


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss_sharded")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _make(seed=21, pop=128, G=3, mesh=None, sharded=None, **kwargs):
    abc = pt.ABCSMC(
        _gauss_model(), pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.PNormDistance(p=2), population_size=pop,
        eps=pt.MedianEpsilon(), seed=seed, mesh=mesh, sharded=sharded,
        fused_generations=G, **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS})
    return abc


def _history_arrays(h):
    """Everything a bit-identity claim covers: epsilon trail plus every
    generation's (theta, weight, distance) arrays."""
    pops = h.get_all_populations().query("t >= 0")
    out = {"eps": pops["epsilon"].to_numpy()}
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        out[f"theta_{t}"] = df["theta"].to_numpy()
        out[f"w_{t}"] = np.asarray(w)
        out[f"d_{t}"] = h.get_weighted_distances(
            int(t))["distance"].to_numpy()
    return out


def _moments(h):
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
    return mu, sd


# ------------------------------------------------------------ parity

class TestShardedParity:
    def test_mesh_bit_identical_to_virtual_shards(self):
        """The lane-key reduction contract: an 8-device shard_map run and
        the SAME reduction vmapped over 8 virtual shards on one device
        produce bit-identical Histories — sharding is an execution
        choice, never a statistical one."""
        abc_v = _make(seed=21, sharded=8)
        assert abc_v._sharded_n() == 8
        h_v = abc_v.run(max_nr_populations=7)

        abc_m = _make(seed=21, mesh=_mesh())
        assert abc_m._sharded_n() == 8  # auto: mesh width
        h_m = abc_m.run(max_nr_populations=7)

        a, b = _history_arrays(h_m), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k], err_msg=f"mesh vs virtual shards diverged "
                                    f"at {k}")
        snap = abc_m._engine.snapshot()
        assert snap["mesh"]["devices"] == 8
        assert snap["mesh"]["imbalance"] >= 1.0
        assert len(snap["mesh"]["rounds_per_device"]) == 8

    # width 4 stays in the fast lane; narrower widths re-assert the
    # same contract in the slow lane (tier-1 wall budget)
    @pytest.mark.parametrize("width", [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        4,
    ])
    def test_divisor_width_bit_identical_to_virtual_shards(self, width):
        """Round 15 width-independence: 8 shards on a NARROWER mesh
        (each device vmapping 8/width virtual shards inside the
        shard_map — the hybrid execution) stay bit-identical to the
        virtual-shard reference. This is the kernel contract the
        serving scheduler's re-place-on-any-width story stands on."""
        abc_v = _make(seed=23, sharded=8)
        h_v = abc_v.run(max_nr_populations=4)

        abc_h = _make(seed=23, mesh=_mesh(width), sharded=8)
        assert abc_h._sharded_n() == 8
        h_h = abc_h.run(max_nr_populations=4)

        a, b = _history_arrays(h_h), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=(f"width-{width} hybrid diverged from virtual "
                         f"shards at {k}"))

    def test_sharded_statistical_parity_with_single_device(self):
        """Different reductions of the same proposal stream: the sharded
        run must agree with the plain single-device run on the posterior
        (and both with the conjugate analytic answer)."""
        h_s = _make(seed=23).run(max_nr_populations=6)
        h_m = _make(seed=23, mesh=_mesh()).run(max_nr_populations=6)
        mu_s, sd_s = _moments(h_s)
        mu_m, sd_m = _moments(h_m)
        assert mu_m == pytest.approx(POST_MU, abs=0.25)
        assert mu_m == pytest.approx(mu_s, abs=0.2)
        assert sd_m == pytest.approx(sd_s, abs=0.15)

    def test_multimodel_sharded(self):
        """K>1 rides the sharded kernel: model ids travel with the
        gathered scalar columns, refits stay per-model masked."""
        from pyabc_tpu.models import model_selection as msel

        models, priors, analytic = msel.tractable_pair()
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=600, eps=pt.MedianEpsilon(),
                        seed=22, mesh=_mesh(), sharded=True,
                        fused_generations=3)
        assert abc._sharded_n() == 8
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        probs = h.get_model_probabilities(h.max_t)
        expected = analytic(X_OBS)
        for m in range(2):
            p = float(probs["p"].get(m, 0.0))
            assert p == pytest.approx(expected[m], abs=0.2), (m, p)


# ------------------------------------------------------- uneven shards

class TestUnevenShards:
    @pytest.mark.parametrize("pop", [300, 100])
    def test_population_not_divisible_by_mesh(self, pop):
        """pop % 8 != 0: leading shards take the remainder (static
        quotas), padding rows never leak — every persisted generation
        has exactly ``pop`` particles with positive total weight."""
        abc = _make(seed=31, pop=pop, mesh=_mesh(), sharded=True)
        h = abc.run(max_nr_populations=5)
        counts = h.get_nr_particles_per_population()
        for t in range(h.max_t + 1):
            assert counts[t] == pop, (t, counts[t])
            df, w = h.get_distribution(0, t)
            assert len(df) == pop
            w = np.asarray(w)
            assert np.all(np.isfinite(w)) and w.sum() == pytest.approx(1.0)
            assert np.all(np.isfinite(df["theta"].to_numpy()))
        mu, _ = _moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.3)

    def test_shard_quota_and_merge_index(self):
        from pyabc_tpu.ops.shard import merge_index, shard_quota_host

        q = shard_quota_host(300, 8)
        assert q.sum() == 300 and q.max() - q.min() <= 1
        idx = merge_index(300, 8, 64)
        assert len(idx) == 300
        # shard-blocked, dense within each shard
        assert idx[0] == 0 and idx[q[0]] == 64
        with pytest.raises(ValueError):
            merge_index(300, 8, 16)  # quota 38 > per-shard capacity 16


# ------------------------------------------------- sharding mechanics

class TestShardingMechanics:
    def test_outs_genuinely_sharded_and_merge_in_fetch(self):
        """The chunk outputs' row leaves live sharded across the 8
        devices (each holds its reservoir shard, not a replica); the
        packed fetch tree is the merged dense layout."""
        from pyabc_tpu.inference.dispatch import DispatchEngine

        captured = {}
        orig = DispatchEngine._fetch_tree

        def spy(self, res_i, t_at, g_lim):
            sh = res_i["outs"]["theta"].sharding
            captured.setdefault("spec", sh.spec if isinstance(
                sh, NamedSharding) else None)
            captured.setdefault(
                "shard_shapes",
                {s.data.shape
                 for s in res_i["outs"]["theta"].addressable_shards},
            )
            return orig(self, res_i, t_at, g_lim)

        DispatchEngine._fetch_tree = spy
        try:
            abc = _make(seed=41, pop=128, G=3, mesh=_mesh())
            h = abc.run(max_nr_populations=4)
        finally:
            DispatchEngine._fetch_tree = orig
        assert h.n_populations == 4
        # n_cap = 128 -> 16 rows per device; G=3 scan axis unsharded
        assert captured["spec"] == P(None, "particles")
        assert captured["shard_shapes"] == {(3, 16, 1)}

    def test_per_shard_rng_lanes_distinct(self):
        """Each shard proposes from its own lane-key block: a
        generation's accepted thetas contain no cross-shard duplicates
        (distinct PRNG lanes, not a replicated draw)."""
        abc = _make(seed=43, pop=128, mesh=_mesh())
        h = abc.run(max_nr_populations=3)
        df, _ = h.get_distribution(0, h.max_t)
        th = df["theta"].to_numpy()
        # merged layout is shard-blocked (16 rows per shard at pop 128):
        # no shard block may replicate another, and the accepted set is
        # overwhelmingly distinct (the f16 wire dtype may collapse a few
        # near-identical draws, so exact all-unique is too strict)
        blocks = th.reshape(8, 16)
        for i in range(8):
            for j in range(i + 1, 8):
                assert not np.array_equal(blocks[i], blocks[j]), (i, j)
        assert len(np.unique(th)) >= int(0.9 * len(th))


# ------------------------------------- engine invariants under sharding

class TestShardedEngine:
    def test_sync_budget_holds(self, monkeypatch):
        """The row merge rides the packed fetch: a sharded run pays the
        same syncs as an unsharded one — asserted STRICT (a budget
        violation raises instead of warning)."""
        monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
        abc = _make(seed=51, mesh=_mesh())
        abc.run(max_nr_populations=7)
        report = abc._engine.sync_budget_report()
        assert report["ok"], report
        assert report["syncs"] <= report["chunks"] + 8

    def test_speculative_rollback_bit_identical(self):
        """A stopping-rule hit with speculative sharded chunks in flight
        rolls them back unpersisted: History bit-identical to the
        depth-1 run of the same seed (rollback stays bit-identical per
        device — the carry chain and per-shard reservoirs never leak
        into the db)."""
        mesh = _mesh()
        probe = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=1)
        h_probe = probe.run(max_nr_populations=6)
        eps_trail = h_probe.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        assert len(eps_trail) >= 4
        min_eps = float(eps_trail[3])

        reg = MetricsRegistry()
        spec = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=4,
                     metrics=reg)
        spec.adopt_device_context(probe)
        h_spec = spec.run(minimum_epsilon=min_eps, max_nr_populations=12)
        assert spec._engine.speculative_rollbacks >= 1
        assert reg.snapshot()[
            "pyabc_tpu_speculative_rollbacks_total"] >= 1

        ref = _make(seed=77, G=2, mesh=mesh, fetch_pipeline_depth=1)
        ref.adopt_device_context(probe)
        h_ref = ref.run(minimum_epsilon=min_eps, max_nr_populations=12)

        a, b = _history_arrays(h_spec), _history_arrays(h_ref)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k], err_msg=f"sharded speculative run diverged "
                                    f"at {k}")
        assert h_spec.n_populations == h_ref.n_populations <= 6

    def test_health_poison_recovery_under_sharding(self):
        """The in-kernel health word still fires sharded (NaN flag is a
        cross-shard reduction) and recovery rolls back to a healthy
        carry: the poisoned run completes with the clean run's
        posterior."""
        from pyabc_tpu.resilience.faults import (
            FaultPlan,
            FaultRule,
            install_fault_plan,
            uninstall_fault_plan,
        )

        mesh = _mesh()
        clean = _make(seed=61, mesh=mesh)
        h_clean = clean.run(max_nr_populations=7)

        install_fault_plan(FaultPlan([
            FaultRule(site="device.carry", kind="nan_poison", after=1,
                      max_fires=1),
        ]))
        try:
            poisoned = _make(seed=61, mesh=mesh)
            poisoned.adopt_device_context(clean)
            h_p = poisoned.run(max_nr_populations=7)
        finally:
            uninstall_fault_plan()
        assert len(poisoned.health_supervisor.trail) >= 1
        a, b = _history_arrays(h_clean), _history_arrays(h_p)
        for k in a:
            np.testing.assert_allclose(
                a[k], b[k], rtol=1e-6, atol=1e-7,
                err_msg=f"poisoned sharded run diverged at {k}")


# ----------------------------------------- adaptive mechanisms (round 16)
#
# ISSUE 12: adaptive distances, stochastic acceptors and per-generation
# population schedules ride the sharded kernel with scalar-column-only
# per-generation collectives — and the mesh bit-identity contract
# extends to them VERBATIM: an 8-device run equals the virtual-shard
# run bit for bit, at every divisor width, with the sync budget
# untouched.

def _gauss2_model_ad():
    @pt.JaxModel.from_function(["theta"], name="gauss2_adaptive")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key),
                "y": 10.0 * theta[0] + jax.random.normal(key)}

    return model


def _make_adaptive(seed=121, mesh=None, sharded=None, pop=128, G=3,
                   **kwargs):
    """AdaptivePNormDistance (std scale — moment-expressible) + a
    per-generation population schedule: two of the three adaptive
    mechanisms in one config (the stochastic acceptor is statistically
    exclusive with a p-norm distance — it needs a kernel density — so
    it gets its own twin config below)."""
    from pyabc_tpu.distance.scale import standard_deviation

    abc = pt.ABCSMC(
        _gauss2_model_ad(),
        pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.AdaptivePNormDistance(p=2, scale_function=standard_deviation),
        population_size=pt.ListPopulationSize(
            [pop, pop - 28, pop, pop - 60, pop, pop]),
        eps=pt.MedianEpsilon(), seed=seed, mesh=mesh, sharded=sharded,
        fused_generations=G, **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS, "y": 10.0 * X_OBS})
    return abc


def _make_noisy(seed=122, mesh=None, sharded=None, pop=256, G=3,
                eps=None, **kwargs):
    """StochasticAcceptor + Temperature schemes + a per-generation
    population schedule on the sharded kernel."""
    @pt.JaxModel.from_function(["theta"], name="det_noisy_sharded")
    def model(key, theta):
        return {"x": theta[0]}

    abc = pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        pt.IndependentNormalKernel(var=[0.3**2]),
        population_size=pt.ListPopulationSize(
            [pop, pop - 56, pop, pop - 120, pop, pop]),
        eps=eps if eps is not None else pt.Temperature(),
        acceptor=pt.StochasticAcceptor(),
        seed=seed, mesh=mesh, sharded=sharded, fused_generations=G,
        **kwargs,
    )
    abc.new("sqlite://", {"x": 0.8})
    return abc


class TestAdaptiveSharded:
    def test_adaptive_distance_pop_schedule_mesh_bit_identical(self):
        """The headline contract: an adaptive-distance + population-
        schedule config runs the sharded kernel, and the 8-device mesh
        run is BIT-identical to the virtual-shard run — epsilon trail,
        thetas, weights, distances, every generation."""
        abc_v = _make_adaptive(sharded=8)
        assert abc_v._sharded_n() == 8
        h_v = abc_v.run(max_nr_populations=6)

        abc_m = _make_adaptive(mesh=_mesh())
        assert abc_m._sharded_n() == 8
        h_m = abc_m.run(max_nr_populations=6)

        a, b = _history_arrays(h_m), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=f"adaptive mesh vs virtual diverged at {k}")
        # the adaptive weights refit each generation (scale state is
        # live, not frozen at calibration)
        w = abc_m.distance_function.weights
        assert len(w) >= 3
        assert not np.allclose(w[1], w[2])

    # width 4 (the widest mesh, the most collective traffic) stays in
    # the fast lane; the narrower widths re-assert the same
    # pure-function-of-n_shards contract and ride the slow lane to keep
    # tier-1 inside its wall budget
    @pytest.mark.parametrize("width", [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        4,
    ])
    def test_adaptive_divisor_width_bit_identical(self, width):
        """Width-independence extends verbatim to the adaptive config:
        the scale moments, refit weights and recomputed distances are a
        pure function of n_shards, not the mesh width."""
        abc_v = _make_adaptive(seed=131, sharded=8)
        h_v = abc_v.run(max_nr_populations=4)

        abc_h = _make_adaptive(seed=131, mesh=_mesh(width), sharded=8)
        assert abc_h._sharded_n() == 8
        h_h = abc_h.run(max_nr_populations=4)

        a, b = _history_arrays(h_h), _history_arrays(h_v)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=(f"adaptive width-{width} diverged from "
                         f"virtual shards at {k}"))

    # the default schemes exercise the record-reweighting path in the
    # fast lane; the exp-decay ladder re-asserts the same contract over
    # a longer trail and rides the slow lane (tier-1 wall budget)
    @pytest.mark.parametrize("schemes", [
        "default",
        pytest.param("exp_decay", marks=pytest.mark.slow),
    ])
    def test_stochastic_acceptor_schedule_mesh_bit_identical(
            self, schemes):
        """Noisy ABC shards: temperature/pdf-norm recursions are
        replicated scalar adaptation, the AcceptanceRateScheme's record
        reweighting reads the ring's gathered scalar columns only — and
        the mesh run equals the virtual-shard run bit for bit. The
        default schemes exercise the record reweighting (cooling fast);
        the exp-decay ladder keeps the trail long enough to cross chunk
        boundaries with the temperature carried on device."""
        from pyabc_tpu.epsilon.temperature import ExpDecayFixedIterScheme

        eps_of = (
            (lambda: pt.Temperature()) if schemes == "default"
            else (lambda: pt.Temperature(
                schemes=[ExpDecayFixedIterScheme()]))
        )
        abc_v = _make_noisy(sharded=8, eps=eps_of())
        if schemes == "default":
            # horizon-needing schemes resolve capability only after
            # eps.initialize (inside run) learns max_nr_populations
            assert abc_v._sharded_n() == 8
        h_v = abc_v.run(max_nr_populations=6)
        assert abc_v._engine.mesh_shards == 8  # ran the sharded kernel
        if schemes == "exp_decay":
            assert h_v.n_populations >= 4  # crosses a chunk boundary

        abc_m = _make_noisy(mesh=_mesh(), eps=eps_of())
        h_m = abc_m.run(max_nr_populations=6)

        a, b = _history_arrays(h_m), _history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=f"stochastic mesh vs virtual diverged at {k}")
        # the temperature trail actually descended through the schemes
        eps = a["eps"]
        assert eps[-1] <= eps[0]

    def test_adaptive_aggregated_sharded_parity(self):
        """AdaptiveAggregatedDistance: the per-generation 1/scale
        reweighting of sub-distance value columns rides the same moment
        reduction (span over value columns)."""
        def make(mesh=None, sharded=None):
            abc = pt.ABCSMC(
                _gauss2_model_ad(),
                pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
                pt.AdaptiveAggregatedDistance(
                    [pt.PNormDistance(p=2), pt.PNormDistance(p=1)]),
                population_size=128, eps=pt.MedianEpsilon(), seed=141,
                mesh=mesh, sharded=sharded, fused_generations=3,
            )
            abc.new("sqlite://", {"x": X_OBS, "y": 10.0 * X_OBS})
            return abc

        h_v = make(sharded=8).run(max_nr_populations=4)
        h_m = make(mesh=_mesh()).run(max_nr_populations=4)
        a, b = _history_arrays(h_m), _history_arrays(h_v)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=f"aggregated mesh vs virtual diverged at {k}")

    def test_sync_count_identical_to_non_adaptive(self, monkeypatch):
        """Satellite regression (strict SyncLedger): the adaptive scale
        reduction rides EXISTING collectives — an adaptive sharded run
        pays exactly the same blocking host round trips as the
        non-adaptive sharded run on the same schedule."""
        monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
        mesh = _mesh()

        plain = _make(seed=151, pop=128, mesh=mesh)
        plain.run(max_nr_populations=5)
        plain_rep = plain._engine.sync_budget_report()

        from pyabc_tpu.distance.scale import standard_deviation

        adaptive = pt.ABCSMC(
            _gauss_model(),
            pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
            pt.AdaptivePNormDistance(
                p=2, scale_function=standard_deviation),
            population_size=128, eps=pt.MedianEpsilon(), seed=151,
            mesh=mesh, fused_generations=3,
        )
        adaptive.new("sqlite://", {"x": X_OBS})
        assert adaptive._sharded_n() == 8
        adaptive.run(max_nr_populations=5)
        adaptive_rep = adaptive._engine.sync_budget_report()

        assert adaptive_rep["ok"] and plain_rep["ok"]
        assert adaptive_rep["chunks"] == plain_rep["chunks"]
        assert adaptive_rep["syncs"] == plain_rep["syncs"], (
            "the scale reduction added a blocking round trip: "
            f"{adaptive_rep} vs {plain_rep}")

    def test_mesh_snapshot_exports_collective_accounting(self):
        """Satellite: the new cross-shard traffic is visible in the
        engine snapshot's mesh block (and through it in
        /api/observability) — row collectives counted, per-generation
        scale-reduction bytes reported."""
        from pyabc_tpu.observability import observability_snapshot

        abc = _make_adaptive(seed=161, mesh=_mesh(),
                             metrics=MetricsRegistry())
        abc.run(max_nr_populations=4)
        snap = abc._engine.snapshot()
        mesh_block = snap["mesh"]
        # one merge gather per chunk + one theta all-gather per refit
        assert mesh_block["row_collectives_total"] >= 2
        # 6 moment rows x 2 stats x 4 bytes x 8 shards
        assert mesh_block["scale_reduction_bytes_per_gen"] == 384
        reg = abc.metrics.snapshot()
        assert reg.get("pyabc_tpu_mesh_row_collectives_total", 0) >= 2
        assert reg.get(
            "pyabc_tpu_mesh_scale_reduction_bytes_per_gen") == 384.0
        # the process-wide snapshot (the /api/observability source)
        # carries the same block through the dispatch sources
        glob = observability_snapshot()
        mesh_blocks = [
            d.get("mesh") for d in glob.get("dispatch", [])
            if d.get("mesh")
        ]
        assert any(
            m.get("scale_reduction_bytes_per_gen") == 384
            for m in mesh_blocks
        )


# ----------------------------------------- segmented composition (ISSUE 17)
#
# The segmented early-reject engine runs INSIDE the sharded kernel:
# each shard sweeps retire/refill over its own lane-key block, only the
# existing scalar columns cross devices. The contracts below: the
# divisor-width bit-identity matrix extends verbatim to segmented runs,
# and the strict sync budget is untouched (the per-shard early-reject
# accounting rides the packed fetch).

def _make_segmented(*, mesh=None, sharded=None, seed=71, early="auto",
                    pop=64, G=3, **kwargs):
    from pyabc_tpu.models import gillespie as g

    obs = g.observed_birth_death(n_leaps=100, n_obs=20, segments=5)
    abc = pt.ABCSMC(
        g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5),
        g.birth_death_prior(), pt.PNormDistance(p=2),
        population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
        early_reject=early, mesh=mesh, sharded=sharded,
        fused_generations=G, **kwargs,
    )
    abc.new("sqlite://", obs)
    return abc


def _seg_history_arrays(h):
    """_history_arrays for the 2-parameter birth-death model (the
    gauss helper assumes a single ``theta`` column)."""
    pops = h.get_all_populations().query("t >= 0")
    out = {"eps": pops["epsilon"].to_numpy()}
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        out[f"theta_{t}"] = df.to_numpy()
        out[f"w_{t}"] = np.asarray(w)
        out[f"d_{t}"] = h.get_weighted_distances(
            int(t))["distance"].to_numpy()
    return out


class TestSegmentedSharded:
    # all widths live in the slow lane: they re-assert the same
    # pure-function-of-n_shards contract the fast lane already covers
    # through the width-8 full-mesh cell in tests/test_segment.py
    # (test_sharded_segment_bit_identical_to_virtual)
    @pytest.mark.parametrize("width", [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
    ])
    def test_divisor_width_segmented_bit_identical(self, width):
        """Width-independence extends verbatim to the segmented engine:
        shard-local retire/refill is a pure function of n_shards, not
        the mesh width — 8 shards early-rejecting on a width-`width`
        hybrid mesh equal the virtual-shard reference bit for bit."""
        abc_v = _make_segmented(seed=73, sharded=8)
        assert abc_v._sharded_n() == 8
        h_v = abc_v.run(max_nr_populations=4)

        abc_h = _make_segmented(seed=73, mesh=_mesh(width), sharded=8)
        assert abc_h._sharded_n() == 8
        h_h = abc_h.run(max_nr_populations=4)

        a, b = _seg_history_arrays(h_h), _seg_history_arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=(f"segmented width-{width} hybrid diverged "
                         f"from virtual shards at {k}"))
        retired = sum(
            (h_h.get_telemetry(t) or {}).get("retired_early", 0)
            for t in range(h_h.max_t + 1)
        )
        assert retired > 0

    @pytest.mark.slow
    def test_sync_budget_strict_with_segments(self, monkeypatch):
        """The shard-local segment sweeps and the per-shard retire
        columns add ZERO blocking host round trips: the strict
        SyncLedger budget of the classic sharded run holds unchanged."""
        monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
        abc = _make_segmented(seed=75, mesh=_mesh())
        assert abc._sharded_n() == 8
        abc.run(max_nr_populations=5)
        report = abc._engine.sync_budget_report()
        assert report["ok"], report
        assert report["syncs"] <= report["chunks"] + 8


# ------------------------------------------------------------ gating
#
# Round 16 (ISSUE 12) shrank `_sharded_incapable_reason` to the
# genuinely-impossible cases: adaptive distances with moment-expressible
# scale functions, stochastic acceptors + temperature schemes,
# per-generation weight/population schedules and in-kernel adaptive
# population sizes all SHARD now. The matrix below is the gate's
# contract: every REMOVED reason's config resolves a shard count, and
# every REMAINING reason is reachable with an actionable message naming
# the fallback path and the config change that would shard.

def _gauss2_model():
    @pt.JaxModel.from_function(["theta"], name="gauss2_sharded")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key),
                "y": 10.0 * theta[0] + jax.random.normal(key)}

    return model


def _abc_for_gate(*, dist=None, pop=128, acceptor=None, eps=None,
                  sharded=True, mesh_width=8, **kwargs):
    kwargs.setdefault("fused_generations", 3)
    abc = pt.ABCSMC(
        _gauss2_model(),
        pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
        dist if dist is not None else pt.PNormDistance(p=2),
        population_size=pop,
        eps=eps if eps is not None else pt.MedianEpsilon(),
        acceptor=acceptor, seed=1,
        mesh=_mesh(mesh_width) if mesh_width else None,
        sharded=sharded, **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS, "y": 10.0 * X_OBS})
    return abc


class TestShardedGating:
    # ---- configs the round-16 gate shrink UNLOCKED: each resolves a
    # shard count where round 13 routed it to the GSPMD fallback
    @pytest.mark.parametrize("cfg", [
        "adaptive_distance", "adaptive_aggregated", "stochastic",
        "pop_schedule", "weight_schedule", "adaptive_pop",
    ])
    def test_previously_gated_configs_now_shard(self, cfg):
        from pyabc_tpu.distance.scale import standard_deviation
        from pyabc_tpu.populationstrategy import AdaptivePopulationSize

        kw = {}
        if cfg == "adaptive_distance":
            kw["dist"] = pt.AdaptivePNormDistance(
                p=2, scale_function=standard_deviation)
        elif cfg == "adaptive_aggregated":
            kw["dist"] = pt.AdaptiveAggregatedDistance(
                [pt.PNormDistance(p=2), pt.PNormDistance(p=1)])
        elif cfg == "stochastic":
            kw["dist"] = pt.IndependentNormalKernel(var=[NOISE_SD**2])
            kw["acceptor"] = pt.StochasticAcceptor()
            kw["eps"] = pt.Temperature()
        elif cfg == "pop_schedule":
            kw["pop"] = pt.ListPopulationSize([128, 100, 128, 68, 128])
        elif cfg == "weight_schedule":
            kw["dist"] = pt.PNormDistance(
                p=2, weights={0: [1.0, 2.0], 2: [2.0, 1.0]})
        elif cfg == "adaptive_pop":
            kw["pop"] = AdaptivePopulationSize(
                128, max_population_size=256, min_population_size=64)
        abc = _abc_for_gate(**kw)
        if cfg == "weight_schedule":
            abc.distance_function.initialize(0, None, abc.x_0)
            assert abc._weight_schedule_fused()
        assert abc._sharded_n() == 8, cfg

    # ---- every REMAINING reason: reachable, actionable message
    def test_reason_median_scale_function(self):
        abc = _abc_for_gate(dist=pt.AdaptivePNormDistance(p=2))  # MAD
        with pytest.raises(ValueError, match="moment-decomposable"):
            abc._sharded_n()
        # the message names decomposable alternatives the user can pick
        with pytest.raises(ValueError, match="standard_deviation"):
            abc._sharded_n()

    def test_reason_custom_scale_function(self):
        # a custom scale function has no device twin at all: the config
        # is not even fused-capable, and the reason says so (the host
        # loops serve it — one level further back than the GSPMD path)
        def my_scale(samples, x_0=None):
            import numpy as np

            return np.std(samples, axis=0)

        abc = _abc_for_gate(
            dist=pt.AdaptivePNormDistance(p=2, scale_function=my_scale))
        with pytest.raises(ValueError, match="cannot run fused chunks"):
            abc._sharded_n()

    def test_reason_learned_sumstats(self):
        abc = _abc_for_gate(dist=pt.AdaptivePNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())))
        with pytest.raises(ValueError, match="learned summary"):
            abc._sharded_n()

    def test_reason_not_fused_capable(self):
        abc = _abc_for_gate(fused_generations=1, mesh_width=None,
                            sharded=8)
        with pytest.raises(ValueError, match="cannot run fused chunks"):
            abc._sharded_n()

    def test_reason_non_power_of_two(self):
        abc = _make(seed=1, sharded=3)
        with pytest.raises(ValueError, match="power of two"):
            abc._sharded_n()

    def test_reason_capacity_not_divisible(self):
        abc = _make(seed=1, pop=64, sharded=256)
        with pytest.raises(ValueError, match="divisible"):
            abc._sharded_n()

    def test_auto_mode_falls_back_quietly_for_median_scale(self):
        abc = pt.ABCSMC(
            _gauss_model(),
            pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD)),
            pt.AdaptivePNormDistance(p=2), population_size=128,
            eps=pt.MedianEpsilon(), seed=1, mesh=_mesh(),
            fused_generations=3,
        )
        assert abc._sharded_n() is None  # GSPMD path serves it instead

    def test_mesh_width_must_divide_shard_count(self):
        # fewer shards than devices cannot spread over the mesh
        abc = _make(seed=1, mesh=_mesh(), sharded=4)
        with pytest.raises(ValueError, match="must divide"):
            abc._sharded_n()

    def test_divisor_width_mesh_runs_hybrid_shards(self):
        """Round 15 (mesh-aware serving): the mesh width only has to
        DIVIDE the shard count — each device vmaps its block of virtual
        shards, so an n-shard checkpoint re-places on any divisor-width
        sub-mesh."""
        assert _make(seed=1, mesh=_mesh(2), sharded=8)._sharded_n() == 8
        assert _make(seed=1, mesh=_mesh(4), sharded=8)._sharded_n() == 8
        # width == shards runs the same vmapped program over a
        # singleton virtual-shard block (codegen-aligned, round 16)
        assert _make(seed=1, mesh=_mesh(8), sharded=8)._sharded_n() == 8

    # ---- round 18: the PROCESS-COUNT gate is lifted; the remaining
    # multi-host incapabilities are topology mistakes, each with an
    # actionable reason (tested on fake multi-process meshes — the real
    # 2-process rig lives in tests/test_multihost.py)
    def test_multihost_even_contiguous_mesh_shards(self):
        abc = _make(seed=1, sharded=8)
        abc.mesh = _FakeMesh([0, 0, 0, 0, 1, 1, 1, 1])
        assert abc._sharded_n() == 8

    def test_reason_multihost_uneven_device_counts(self):
        abc = _make(seed=1, sharded=8)
        abc.mesh = _FakeMesh([0, 0, 0, 0, 0, 1, 1, 1])
        with pytest.raises(ValueError, match="UNEVEN per-process"):
            abc._sharded_n()
        # the message names the fix
        with pytest.raises(ValueError, match="dist.global_mesh"):
            abc._sharded_n()

    def test_reason_multihost_interleaved_blocks(self):
        abc = _make(seed=1, sharded=8)
        abc.mesh = _FakeMesh([0, 1, 0, 1, 0, 1, 0, 1])
        with pytest.raises(ValueError, match="interleaves"):
            abc._sharded_n()

    def test_multihost_auto_mode_falls_back_with_telemetry(self):
        """sharded unset (auto): a broken multi-host topology falls back
        QUIETLY to the GSPMD path, recording the reason at the `sharded`
        capability gate."""
        abc = _make(seed=1)
        abc.mesh = _FakeMesh([0, 0, 0, 0, 0, 1, 1, 1])
        assert abc._sharded_n() is None
        gates = {f["gate"] for f in abc._capability_fallbacks}
        assert "sharded" in gates
        reasons = " ".join(
            f["reason"] for f in abc._capability_fallbacks)
        assert "UNEVEN per-process" in reasons


class _FakeDevice:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeMesh:
    """Just enough mesh for the _sharded_n gate: ``.devices`` holding
    devices with a ``process_index`` (an ATTRIBUTE read — the gate never
    calls into the runtime, DIST001)."""

    def __init__(self, process_indices):
        self.devices = np.asarray(
            [_FakeDevice(p) for p in process_indices], dtype=object)
