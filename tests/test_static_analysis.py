"""abc-lint engine tests + the repo-wide zero-unbaselined gate.

Three layers:

1. golden fixture snippets per rule — fires / clean / suppressed /
   baselined — plus engine mechanics (directive targeting, required
   suppression reasons, import-alias resolution, baseline staleness);
2. mutation tests against REAL tree files: un-ledgering one real fetch
   site must make SYNC001 fire, un-splitting a real model's keys must
   make RNG001 fire — proving the rules bite on production code, not
   just fixtures;
3. ``test_repo_is_lint_clean`` — the tier-1 gate: the whole default scan
   set reports ZERO unbaselined findings against the committed baseline,
   and the baseline itself is not stale.
"""
from pathlib import Path

import pytest

from pyabc_tpu.analysis import (
    DEFAULT_TARGETS,
    FileContext,
    all_rules,
    baseline,
    iter_python_files,
    run_analysis,
)
from pyabc_tpu.analysis.cli import main as lint_main
from pyabc_tpu.analysis.engine import (
    META_BAD_DIRECTIVE,
    AnalysisResult,
    Finding,
)
from pyabc_tpu.analysis.rules.clock import Clock001
from pyabc_tpu.analysis.rules.collectives import Mesh001
from pyabc_tpu.analysis.rules.dispatch import Disp001
from pyabc_tpu.analysis.rules.exceptions import Exc001
from pyabc_tpu.analysis.rules.locks import Lock001
from pyabc_tpu.analysis.rules.rng import Rng001
from pyabc_tpu.analysis.rules.sync import Sync001
from pyabc_tpu.analysis.rules.telemetry import Telem001

REPO = Path(__file__).resolve().parent.parent


def check(rule, src, rel="pyabc_tpu/fixture.py"):
    """Run one rule over an inline snippet; returns (open, suppressed)."""
    ctx = FileContext(Path(rel), rel, src)
    findings = []
    for f in rule.check(ctx):
        sup = ctx.find_suppression(f.rule, f.line)
        if sup is not None:
            f.status, f.reason = "suppressed", sup.reason
        findings.append(f)
    return ([f for f in findings if f.status == "open"],
            [f for f in findings if f.status == "suppressed"])


# ---------------------------------------------------------------- SYNC001

SYNC_FIRES = """
import jax
def fetch(out):
    return jax.device_get(out)
"""

SYNC_CLEAN = """
import jax
def fetch(self, out):
    host = jax.device_get(out)
    self.sync_ledger.record("chunk_fetch", 128)
    return host
"""

SYNC_SUPPRESSED = """
import jax
def fetch(out):
    # abc-lint: disable=SYNC001 standalone probe outside any run
    return jax.device_get(out)
"""


def test_sync001_fires_on_unledgered_fetch():
    open_, _ = check(Sync001(), SYNC_FIRES)
    assert len(open_) == 1 and open_[0].rule == "SYNC001"
    assert "SyncLedger" in open_[0].message


def test_sync001_clean_when_scope_records():
    open_, _ = check(Sync001(), SYNC_CLEAN)
    assert open_ == []


def test_sync001_suppression_with_reason():
    open_, sup = check(Sync001(), SYNC_SUPPRESSED)
    assert open_ == [] and len(sup) == 1
    assert sup[0].reason == "standalone probe outside any run"


def test_sync001_materializers_device_marked_only():
    src = """
import numpy as np
def f(self, rec_dev, host_rows):
    a = np.asarray(host_rows)          # host value: legal
    b = np.asarray(rec_dev)            # device-marked: flagged
    c = float(self.eps_dev)            # device-marked: flagged
    d = rec_dev.item()                 # device-marked: flagged
    return a, b, c, d
"""
    open_, _ = check(Sync001(), src)
    assert sorted(f.line for f in open_) == [5, 6, 7]


def test_sync001_nested_scope_needs_own_ledger():
    # ledger evidence in the OUTER function must not excuse a closure
    src = """
import jax
def outer(self, out):
    self.sync_ledger.record("x")
    def fetch():
        return jax.device_get(out)
    return fetch
"""
    open_, _ = check(Sync001(), src)
    assert len(open_) == 1 and open_[0].line == 6


def test_sync001_mutation_unledgering_real_fetch_site_fails():
    """THE mutation guard: removing the SyncLedger record from a real
    fetch site in sampler/batched.py must make SYNC001 fire there."""
    path = REPO / "pyabc_tpu" / "sampler" / "batched.py"
    src = path.read_text()
    assert "self.sync_ledger.record" in src
    rel = "pyabc_tpu/sampler/batched.py"
    open_, _ = check(Sync001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src.replace("self.sync_ledger.record", "self._not_recording")
    open_m, _ = check(Sync001(), mutated, rel)
    assert len(open_m) >= 1, (
        "un-ledgering every record call left SYNC001 silent — the rule "
        "no longer guards the PR-2 sync accounting")


# --------------------------------------------------------------- CLOCK001

def test_clock001_fires_including_aliases():
    src = """
import time as _t
from datetime import datetime as dtt
def f():
    a = _t.monotonic()
    b = dtt.now()
    return a, b
"""
    open_, _ = check(Clock001(), src)
    assert sorted(f.line for f in open_) == [5, 6]


def test_clock001_sleep_and_constructors_legal():
    src = """
import time, datetime
def f():
    time.sleep(0.1)
    d = datetime.datetime(2026, 1, 1)
    return d
"""
    open_, _ = check(Clock001(), src)
    assert open_ == []


def test_clock001_scope_excludes_profile_gen():
    assert not Clock001().applies_to("profile_gen.py")
    assert Clock001().applies_to("bench.py")
    assert Clock001().applies_to("pyabc_tpu/sge/sge.py")


def test_clock001_suppressed_in_systemclock_only():
    """The clock implementation's two raw reads are suppressed WITH
    reasons; repo-wide there are no other CLOCK001 sites."""
    files = iter_python_files([REPO / "pyabc_tpu", REPO / "bench.py"])
    res = run_analysis(REPO, files, [Clock001()])
    assert res.open == [], [f.to_dict() for f in res.open]
    assert {f.path for f in res.suppressed} == {
        "pyabc_tpu/observability/clock.py"}
    assert all(f.reason for f in res.suppressed)


# ----------------------------------------------------------------- RNG001

def test_rng001_fires_on_reuse_and_loop_carry():
    src = """
import jax
def bad(key):
    a = jax.random.normal(key)
    return a + jax.random.uniform(key)
def loop_bug(key, xs):
    tot = 0.0
    for x in xs:
        tot += jax.random.normal(key)
    return tot
"""
    open_, _ = check(Rng001(), src)
    assert len(open_) == 2
    assert {f.line for f in open_} == {5, 9}


def test_rng001_clean_on_split_fold_and_branches():
    src = """
import jax
def split_ok(key):
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1) + jax.random.uniform(k2)
def fold_ok(key, xs):
    tot = 0.0
    for i in range(3):
        key = jax.random.fold_in(key, i)
        tot += jax.random.normal(key)
    return tot
def branch_ok(key, flag):
    if flag:
        return jax.random.normal(key)
    return jax.random.uniform(key)
"""
    open_, _ = check(Rng001(), src)
    assert open_ == []


def test_rng001_mutation_unsplitting_real_model_fails():
    """models/lotka_volterra.py derives k1/k2 via split; feeding the
    root key to both noise draws instead must fire RNG001. (The first
    full-tree run found ZERO real reuse — the split discipline holds —
    so the real-tree evidence for this rule is this mutation guard.)"""
    path = REPO / "pyabc_tpu" / "models" / "lotka_volterra.py"
    src = path.read_text()
    rel = "pyabc_tpu/models/lotka_volterra.py"
    open_, _ = check(Rng001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    for frag in ("jax.random.normal(k1, ", "jax.random.normal(k2, "):
        assert src.count(frag) == 1, frag
    mutated = (src
               .replace("jax.random.normal(k1, ", "jax.random.normal(key, ")
               .replace("jax.random.normal(k2, ", "jax.random.normal(key, "))
    open_m, _ = check(Rng001(), mutated, rel)
    assert len(open_m) == 1 and "key" in open_m[0].message


# ----------------------------------------------------------------- EXC001

def test_exc001_fires_on_multiline_equivalents():
    src = """
def f(xs):
    for x in xs:
        try:
            x()
        except Exception:
            continue
    try:
        xs[0]()
    except (ValueError, BaseException):
        return
"""
    open_, _ = check(Exc001(), src)
    assert len(open_) == 2


def test_exc001_narrow_or_traced_handlers_legal():
    src = """
def f(x, log):
    try:
        x()
    except FileNotFoundError:
        pass
    try:
        x()
    except Exception as e:
        log.warning("boom: %r", e)
"""
    open_, _ = check(Exc001(), src)
    assert open_ == []


# ---------------------------------------------------------------- LOCK001

LOCK_SRC = """
import threading
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # abc-lint: guarded-by=_lock
    def good(self):
        with self._lock:
            self._items.append(1)
    def bad(self):
        return len(self._items)
    def _drain_locked(self):
        self._items.clear()
    def bad_call(self):
        self._drain_locked()
    # abc-lint: holds=_lock
    def assumed(self):
        return self._items[0]
"""


def test_lock001_fires_outside_lock_and_on_unlocked_locked_call():
    open_, _ = check(Lock001(), LOCK_SRC)
    assert {f.line for f in open_} == {11, 15}


def test_lock001_with_block_suffix_and_holds_exempt():
    open_, _ = check(Lock001(), LOCK_SRC)
    lines = {f.line for f in open_}
    # good/with (8-9), _locked suffix body (13), holds directive (18)
    assert not lines & {8, 9, 13, 18}


def test_lock001_real_tree_contracts_hold():
    """The annotated classes (EvalBroker, SyncLedger, MetricsRegistry)
    pass their own contracts — the `_touch` -> `_touch_locked` rename
    was this rule's real-tree fix."""
    files = [REPO / "pyabc_tpu" / "broker" / "broker.py",
             REPO / "pyabc_tpu" / "observability" / "sync.py",
             REPO / "pyabc_tpu" / "observability" / "metrics.py"]
    res = run_analysis(REPO, files, [Lock001()])
    assert res.open == [], [f.to_dict() for f in res.open]
    # and the contracts are actually declared (not silently dropped)
    broker_src = files[0].read_text()
    assert broker_src.count("abc-lint: guarded-by=_lock") >= 10


# --------------------------------------------------------------- DISP001

def test_disp001_fires_outside_engine_module():
    src = """
def sneak_dispatch(self, carry, t):
    return self.kern_cache.multigen_kernel(8, 256, 1, 4, 8)
def sneak_fetch(self, ctx, outs):
    return ctx.fetch_pack_kernel(n_keep=64, dtype_name="float16")(outs)
def sneak_round(self, ctx, B, mode, key, dyn):
    return ctx.round_kernel(B, mode)(key, dyn)
"""
    open_, _ = check(Disp001(), src, "pyabc_tpu/inference/smc.py")
    assert len(open_) == 3, [f.to_dict() for f in open_]


def test_disp001_engine_and_util_exempt():
    src = "def build(self, ctx):\n    return ctx.multigen_kernel(1)\n"
    assert not Disp001().applies_to("pyabc_tpu/inference/dispatch.py")
    assert not Disp001().applies_to("pyabc_tpu/inference/util.py")
    assert not Disp001().applies_to("tests/test_mesh.py")
    assert Disp001().applies_to("pyabc_tpu/inference/smc.py")
    assert Disp001().applies_to("pyabc_tpu/sampler/batched.py")
    open_, _ = check(Disp001(), src, "pyabc_tpu/inference/x.py")
    assert len(open_) == 1


def test_disp001_suppression_with_reason():
    src = """
def probe(ctx, outs):
    # abc-lint: disable=DISP001 standalone diagnostic outside any run
    return ctx.fetch_pack_kernel(n_keep=8, dtype_name="float32")(outs)
"""
    open_, sup = check(Disp001(), src, "pyabc_tpu/inference/x.py")
    assert open_ == [] and len(sup) == 1


def test_disp001_mutation_direct_dispatch_in_smc_fails():
    """THE mutation guard: re-growing a direct chunk dispatch/fetch in
    smc.py (the three-loop pattern this rule exists to prevent) must
    make DISP001 fire — today's smc.py is clean, a re-added call is a
    finding."""
    path = REPO / "pyabc_tpu" / "inference" / "smc.py"
    src = path.read_text()
    rel = "pyabc_tpu/inference/smc.py"
    open_, _ = check(Disp001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _resurrected_loop(self, ctx, outs):\n"
        "    tree = ctx.fetch_pack_kernel(n_keep=64,\n"
        "                                 dtype_name='float16')(outs)\n"
        "    return tree\n"
    )
    open_m, _ = check(Disp001(), mutated, rel)
    assert len(open_m) >= 1, (
        "a direct fetch_pack_kernel call re-added to smc.py left "
        "DISP001 silent — the engine's single-door invariant is no "
        "longer guarded")


# --------------------------------------------------------------- MESH001

MESH_FIRES = """
import jax
def sneak_reduce(x):
    return jax.lax.psum(x, "particles")
def sneak_gather(x):
    return jax.lax.all_gather(x, "particles", tiled=True)
def sneak_spmd(fn, mesh):
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=None, out_specs=None)
"""

MESH_CLEAN = """
import numpy as np
def quotas(n, shards):
    return np.asarray([n // shards] * shards)
"""

MESH_SUPPRESSED = """
import jax
def probe(x):
    # abc-lint: disable=MESH001 standalone diagnostic outside any run
    return jax.lax.psum(x, "particles")
"""


def test_mesh001_fires_on_collectives_outside_kernel_layer():
    open_, _ = check(Mesh001(), MESH_FIRES, "pyabc_tpu/inference/smc.py")
    assert len(open_) == 3, [f.to_dict() for f in open_]
    assert {"psum", "all_gather", "shard_map"} <= {
        f.message.split("`")[1].split("(")[0] for f in open_
    }


def test_instrumented_set_pins_kernel_layer_files():
    """The INSTRUMENTED set exists so a rename can't silently un-lint a
    kernel-layer module; ISSUE 15 pins the segmented engine's math."""
    from pathlib import Path

    from pyabc_tpu.analysis.engine import INSTRUMENTED

    assert "pyabc_tpu/ops/segment.py" in INSTRUMENTED
    assert "pyabc_tpu/inference/util.py" in INSTRUMENTED
    root = Path(__file__).resolve().parents[1]
    for rel in INSTRUMENTED:
        assert (root / rel).exists(), f"pinned module missing: {rel}"


def test_mesh001_kernel_layer_and_tests_exempt():
    assert not Mesh001().applies_to("pyabc_tpu/inference/util.py")
    assert not Mesh001().applies_to("pyabc_tpu/ops/shard.py")
    assert not Mesh001().applies_to("pyabc_tpu/ops/pack.py")
    assert not Mesh001().applies_to("pyabc_tpu/ops/segment.py")
    assert not Mesh001().applies_to("tests/test_sharded.py")
    assert Mesh001().applies_to("pyabc_tpu/inference/smc.py")
    assert Mesh001().applies_to("pyabc_tpu/inference/dispatch.py")
    assert Mesh001().applies_to("pyabc_tpu/parallel/distributed.py")
    assert Mesh001().applies_to("pyabc_tpu/sampler/batched.py")
    open_, _ = check(Mesh001(), MESH_CLEAN, "pyabc_tpu/inference/x.py")
    assert open_ == []


def test_mesh001_suppression_with_reason():
    open_, sup = check(Mesh001(), MESH_SUPPRESSED,
                       "pyabc_tpu/inference/x.py")
    assert open_ == [] and len(sup) == 1 and sup[0].reason


def test_mesh001_mutation_stray_psum_in_smc_fails():
    """THE mutation guard: a stray collective growing into smc.py (an
    unbudgeted sync path outside the kernel layer) must make MESH001
    fire — today's smc.py is clean, a re-added psum is a finding."""
    path = REPO / "pyabc_tpu" / "inference" / "smc.py"
    src = path.read_text()
    rel = "pyabc_tpu/inference/smc.py"
    open_, _ = check(Mesh001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _stray_mesh_reduce(self, x):\n"
        "    import jax\n"
        "    return jax.lax.psum(x, 'particles')\n"
    )
    open_m, _ = check(Mesh001(), mutated, rel)
    assert len(open_m) >= 1, (
        "a psum re-added to smc.py left MESH001 silent — the "
        "chunk-boundary-only collective contract is no longer guarded")


# --------------------------------------------------------------- TELEM001

def test_telem001_fires_outside_observability_only():
    src = "phase_timings = {}\n"
    open_, _ = check(Telem001(), src, "pyabc_tpu/inference/x.py")
    assert len(open_) == 1
    assert not Telem001().applies_to("pyabc_tpu/observability/tracer.py")
    assert Telem001().applies_to("bench.py")


# ----------------------------------------------------- engine mechanics

def test_suppression_without_reason_is_a_finding():
    src = """
import jax
def fetch(out):
    return jax.device_get(out)  # abc-lint: disable=SYNC001
"""
    ctx = FileContext(Path("x.py"), "pyabc_tpu/x.py", src)
    assert [f.rule for f in ctx.meta_findings] == [META_BAD_DIRECTIVE]
    # and the finding is NOT suppressed by the reasonless directive
    open_, sup = check(Sync001(), src)
    assert len(open_) == 1 and sup == []


def test_standalone_comment_targets_next_code_line():
    src = """
import jax
def fetch(out):
    # abc-lint: disable=SYNC001 probe outside any run
    return jax.device_get(out)
"""
    open_, sup = check(Sync001(), src)
    assert open_ == [] and len(sup) == 1


def test_unknown_directive_is_a_finding():
    ctx = FileContext(Path("x.py"), "pyabc_tpu/x.py",
                      "x = 1  # abc-lint: frobnicate=yes\n")
    assert [f.rule for f in ctx.meta_findings] == [META_BAD_DIRECTIVE]


def test_baseline_requires_reasons(tmp_path):
    p = tmp_path / "b.json"
    p.write_text('{"version": 1, "entries": [{"rule": "SYNC001", '
                 '"path": "x.py", "code": "y", "reason": "  "}]}')
    with pytest.raises(baseline.BaselineError):
        baseline.load(p)


def test_baseline_staleness_fails_lint():
    """A baselined finding that no longer fires must fail: the baseline
    only shrinks."""
    res = AnalysisResult(findings=[])
    baseline.apply(res, [{"rule": "SYNC001", "path": "gone.py",
                          "code": "jax.device_get(x)", "reason": "old"}])
    assert res.stale_baseline and not res.ok


def test_baseline_matches_by_code_not_line():
    f = Finding(rule="SYNC001", path="a.py", line=99, col=0, message="m",
                code="jax.device_get(x)")
    res = AnalysisResult(findings=[f])
    baseline.apply(res, [{"rule": "SYNC001", "path": "a.py",
                          "code": "jax.device_get(x)", "reason": "r"}])
    assert f.status == "baselined" and res.ok


def test_cli_select_ignore_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt = time.time()\n")
    # CLOCK001 does not apply outside pyabc_tpu/, so craft a SYNC case
    bad.write_text("import jax\nx = jax.device_get(1)\n")
    assert lint_main([str(bad), "--no-baseline"]) == 1
    assert lint_main([str(bad), "--no-baseline", "--ignore", "SYNC001"]) == 0
    assert lint_main([str(bad), "--no-baseline", "--select", "EXC001"]) == 0
    out = capsys.readouterr().out
    assert "SYNC001" in out


def test_cli_json_format(tmp_path, capsys):
    import json
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\nx = jax.device_get(1)\n")
    assert lint_main([str(bad), "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is False
    assert payload["counts"]["open_by_rule"] == {"SYNC001": 1}


# --------------------------------------------------------------- ISO001

ISO_FIRES = """
import pyabc_tpu as pt
def sneak_run(spec):
    abc = pt.ABCSMC(spec.models, spec.priors)
    return abc
def sneak_engine(owner, ctx):
    from pyabc_tpu.inference.dispatch import DispatchEngine
    return DispatchEngine(owner, ctx)
def sneak_context(abc, donor):
    abc.adopt_device_context(donor)
    return abc._build_device_ctx()
"""

ISO_CLEAN = """
def describe(spec):
    # describing a run is fine; constructing one is the scheduler's job
    return {"kwargs": {"population_size": spec.population_size}}
"""

ISO_SUPPRESSED = """
import pyabc_tpu as pt
def probe(models, priors):
    # abc-lint: disable=ISO001 offline capability probe, never admitted
    return pt.ABCSMC(models, priors)
"""


def test_iso001_fires_on_unleased_run_construction():
    from pyabc_tpu.analysis.rules.isolation import Iso001

    open_, _ = check(Iso001(), ISO_FIRES, "pyabc_tpu/serving/api.py")
    assert len(open_) == 4, [f.to_dict() for f in open_]
    msgs = " ".join(f.message for f in open_)
    assert "ABCSMC" in msgs and "DispatchEngine" in msgs
    assert "adopt_device_context" in msgs and "_build_device_ctx" in msgs


def test_iso001_scope_is_serving_minus_scheduler():
    from pyabc_tpu.analysis.rules.isolation import Iso001

    r = Iso001()
    # the leased path itself is exempt; everything else in serving/ is in
    assert not r.applies_to("pyabc_tpu/serving/scheduler.py")
    assert r.applies_to("pyabc_tpu/serving/api.py")
    assert r.applies_to("pyabc_tpu/serving/tenant.py")
    assert r.applies_to("pyabc_tpu/serving/admission.py")
    # the rest of the tree constructs runs legitimately
    assert not r.applies_to("pyabc_tpu/inference/smc.py")
    assert not r.applies_to("bench.py")
    assert not r.applies_to("tests/test_serving.py")
    open_, _ = check(r, ISO_CLEAN, "pyabc_tpu/serving/tenant.py")
    assert open_ == []


def test_iso001_suppression_with_reason():
    from pyabc_tpu.analysis.rules.isolation import Iso001

    open_, sup = check(Iso001(), ISO_SUPPRESSED,
                       "pyabc_tpu/serving/api.py")
    assert open_ == [] and len(sup) == 1 and sup[0].reason


def test_iso001_mutation_unleased_run_in_api_fails():
    """THE mutation guard: an ABCSMC construction growing into the
    serving API (a run bypassing admission, leases and fault scoping)
    must make ISO001 fire — today's api.py is clean, a re-added
    construction is a finding."""
    from pyabc_tpu.analysis.rules.isolation import Iso001

    path = REPO / "pyabc_tpu" / "serving" / "api.py"
    src = path.read_text()
    rel = "pyabc_tpu/serving/api.py"
    open_, _ = check(Iso001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _quick_run(spec):\n"
        "    from ..inference.smc import ABCSMC\n"
        "    abc = ABCSMC(spec.models, spec.priors)\n"
        "    return abc.run()\n"
    )
    open_m, _ = check(Iso001(), mutated, rel)
    assert len(open_m) >= 1, (
        "an ABCSMC construction re-added to serving/api.py left ISO001 "
        "silent — the leased-path isolation contract is no longer "
        "guarded")


# ------------------------------------------------------------- PLACE001

PLACE_FIRES = """
import jax
import numpy as np
from jax.sharding import Mesh
def sneak_mesh(width):
    devs = jax.devices()
    return Mesh(np.asarray(devs[:width]), axis_names=("particles",))
def sneak_enum():
    return jax.local_devices(), jax.device_count()
"""

PLACE_CLEAN = """
from . import placement
def place(allocator, tenant_id, width):
    lo = allocator.alloc(width, tenant_id)
    return None if lo is None else placement.build_mesh(lo, width)
"""

PLACE_SUPPRESSED = """
import jax
def probe():
    # abc-lint: disable=PLACE001 offline capability probe, no lease taken
    return len(jax.devices())
"""


def test_place001_fires_on_mesh_and_enumeration():
    from pyabc_tpu.analysis.rules.placement_rule import Place001

    open_, _ = check(Place001(), PLACE_FIRES,
                     "pyabc_tpu/serving/scheduler.py")
    assert len(open_) == 4, [f.to_dict() for f in open_]
    msgs = " ".join(f.message for f in open_)
    assert "Mesh" in msgs and "devices" in msgs
    assert "local_devices" in msgs and "device_count" in msgs


def test_place001_scope_is_serving_minus_placement():
    from pyabc_tpu.analysis.rules.placement_rule import Place001

    r = Place001()
    # the sanctioned topology module is exempt; the rest of serving/ is in
    assert not r.applies_to("pyabc_tpu/serving/placement.py")
    assert r.applies_to("pyabc_tpu/serving/scheduler.py")
    assert r.applies_to("pyabc_tpu/serving/api.py")
    assert r.applies_to("pyabc_tpu/serving/tenant.py")
    # the rest of the tree builds meshes legitimately
    assert not r.applies_to("pyabc_tpu/inference/util.py")
    assert not r.applies_to("pyabc_tpu/parallel/distributed.py")
    assert not r.applies_to("bench.py")
    assert not r.applies_to("tests/test_sharded.py")
    open_, _ = check(r, PLACE_CLEAN, "pyabc_tpu/serving/scheduler.py")
    assert open_ == []


def test_place001_suppression_with_reason():
    from pyabc_tpu.analysis.rules.placement_rule import Place001

    open_, sup = check(Place001(), PLACE_SUPPRESSED,
                       "pyabc_tpu/serving/scheduler.py")
    assert open_ == [] and len(sup) == 1 and sup[0].reason


def test_place001_mutation_stray_mesh_in_scheduler_fails():
    """THE mutation guard: a Mesh construction (or device enumeration)
    growing into the scheduler — placement decided outside the
    allocator's books — must make PLACE001 fire; today's scheduler.py
    is clean, a re-added construction is a finding."""
    from pyabc_tpu.analysis.rules.placement_rule import Place001

    path = REPO / "pyabc_tpu" / "serving" / "scheduler.py"
    src = path.read_text()
    rel = "pyabc_tpu/serving/scheduler.py"
    open_, _ = check(Place001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _quick_mesh(width):\n"
        "    import jax\n"
        "    import numpy as np\n"
        "    from jax.sharding import Mesh\n"
        "    return Mesh(np.asarray(jax.devices()[:width]),\n"
        "                axis_names=('particles',))\n"
    )
    open_m, _ = check(Place001(), mutated, rel)
    assert len(open_m) >= 2, (
        "a Mesh construction re-added to serving/scheduler.py left "
        "PLACE001 silent — the placement-confinement contract is no "
        "longer guarded")


# -------------------------------------------------------------- DIST001

DIST_FIRES = """
import jax
from jax.experimental import multihost_utils
def helper():
    jax.distributed.initialize()
    if jax.process_index() == 0:
        multihost_utils.sync_global_devices("x")
    return jax.process_count()
"""

DIST_CLEAN = """
from ..parallel import distributed as dist
def helper(db):
    dist.initialize()
    # Device.process_index ATTRIBUTE reads inspect a mesh, not the
    # runtime: the multi-host gate in smc.py/util.py stays legal
    n_proc = len({d.process_index for d in mesh.devices.flat})
    return dist.primary_db(db), n_proc
"""

DIST_SUPPRESSED = """
import jax
def probe():
    # abc-lint: disable=DIST001 offline capability probe, no topology change
    return jax.process_count()
"""


def test_dist001_fires_on_runtime_calls():
    from pyabc_tpu.analysis.rules.distributed import Dist001

    open_, _ = check(Dist001(), DIST_FIRES, "pyabc_tpu/inference/smc.py")
    assert len(open_) == 4, [f.to_dict() for f in open_]
    msgs = " ".join(f.message for f in open_)
    assert "jax.distributed.initialize" in msgs
    assert "jax.process_index" in msgs
    assert "multihost_utils" in msgs
    assert "jax.process_count" in msgs


def test_dist001_scope_is_pyabc_minus_distributed():
    from pyabc_tpu.analysis.rules.distributed import Dist001

    r = Dist001()
    # the one sanctioned module is exempt; the rest of the package is in
    assert not r.applies_to("pyabc_tpu/parallel/distributed.py")
    assert r.applies_to("pyabc_tpu/inference/smc.py")
    assert r.applies_to("pyabc_tpu/inference/util.py")
    assert r.applies_to("pyabc_tpu/serving/scheduler.py")
    assert not r.applies_to("bench.py")
    assert not r.applies_to("tests/test_multihost.py")
    open_, _ = check(r, DIST_CLEAN, "pyabc_tpu/inference/smc.py")
    assert open_ == [], [f.to_dict() for f in open_]


def test_dist001_suppression_with_reason():
    from pyabc_tpu.analysis.rules.distributed import Dist001

    open_, sup = check(Dist001(), DIST_SUPPRESSED,
                       "pyabc_tpu/serving/scheduler.py")
    assert open_ == [] and len(sup) == 1 and sup[0].reason


def test_dist001_mutation_process_probe_in_smc_fails():
    """THE mutation guard: a ``jax.process_index()`` probe growing back
    into the SMC loop — per-process host control flow, the divergence
    class the replicated-deterministic contract forbids — must make
    DIST001 fire; today's smc.py is clean (its multi-host gate reads
    Device.process_index attributes only)."""
    from pyabc_tpu.analysis.rules.distributed import Dist001

    path = REPO / "pyabc_tpu" / "inference" / "smc.py"
    src = path.read_text()
    rel = "pyabc_tpu/inference/smc.py"
    open_, _ = check(Dist001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _only_on_primary(fn):\n"
        "    import jax\n"
        "    if jax.process_index() == 0:\n"
        "        return fn()\n"
    )
    open_m, _ = check(Dist001(), mutated, rel)
    assert len(open_m) >= 1, (
        "a jax.process_index() probe re-added to inference/smc.py left "
        "DIST001 silent — the process-topology confinement contract is "
        "no longer guarded")


# --------------------------------------------------------------- REC001

REC_FIRES_OBS = """
import json, os
def leak_metrics(registry, path):
    with open(path, "w") as f:
        json.dump(registry.snapshot(), f)
    os.replace(path + ".tmp", path)
"""

REC_FIRES_WRITE_FLIGHT = """
from ..observability.recorder import write_flight
def hand_rolled_dump(payload, path):
    write_flight(path, payload)
"""

REC_CLEAN = """
def on_fault(tenant):
    # persistence goes through the recorder's own crash-safe path
    tenant.flight.note("fault", reason="lease_reaped")
    return tenant.flight.dump(reason="lease_reaped")
"""

REC_SUPPRESSED = """
def debug_spill(payload, path):
    # abc-lint: disable=REC001 throwaway debug spill, not a flight file
    with open(path, "w") as f:
        f.write(repr(payload))
"""


def test_rec001_fires_on_fs_writes_inside_observability():
    from pyabc_tpu.analysis.rules.recorder_rule import Rec001

    open_, _ = check(Rec001(), REC_FIRES_OBS,
                     "pyabc_tpu/observability/metrics.py")
    assert len(open_) == 2, [f.to_dict() for f in open_]
    msgs = " ".join(f.message for f in open_)
    assert "open" in msgs and "os.replace" in msgs


def test_rec001_fires_on_write_flight_outside_recorder():
    from pyabc_tpu.analysis.rules.recorder_rule import Rec001

    open_, _ = check(Rec001(), REC_FIRES_WRITE_FLIGHT,
                     "pyabc_tpu/serving/scheduler.py")
    assert len(open_) == 1, [f.to_dict() for f in open_]
    assert "FlightRecorder.dump()" in open_[0].message


def test_rec001_scope_is_two_sanctioned_modules():
    from pyabc_tpu.analysis.rules.recorder_rule import Rec001

    r = Rec001()
    # the two sanctioned persistence modules are exempt; the rest of
    # the observability package (and the wider tree) is in
    assert not r.applies_to("pyabc_tpu/observability/recorder.py")
    assert not r.applies_to("pyabc_tpu/observability/export.py")
    assert r.applies_to("pyabc_tpu/observability/metrics.py")
    assert r.applies_to("pyabc_tpu/observability/slo.py")
    assert r.applies_to("pyabc_tpu/serving/scheduler.py")
    assert not r.applies_to("bench.py")
    assert not r.applies_to("tests/test_observability.py")
    # open()/os.replace OUTSIDE observability/ stays legal (checkpoints,
    # History dbs): only the write_flight bypass fires tree-wide
    open_, _ = check(r, REC_FIRES_OBS, "pyabc_tpu/serving/lifecycle.py")
    assert open_ == [], [f.to_dict() for f in open_]
    open_, _ = check(r, REC_CLEAN, "pyabc_tpu/serving/scheduler.py")
    assert open_ == [], [f.to_dict() for f in open_]


def test_rec001_suppression_with_reason():
    from pyabc_tpu.analysis.rules.recorder_rule import Rec001

    open_, sup = check(Rec001(), REC_SUPPRESSED,
                       "pyabc_tpu/observability/metrics.py")
    assert open_ == [] and len(sup) == 1 and sup[0].reason


def test_rec001_mutation_file_write_in_slo_fails():
    """THE mutation guard: a file write growing into the SLO engine —
    telemetry persisted outside the recorder's crash-safe path — must
    make REC001 fire; today's slo.py is clean (it only reads
    instruments and exports gauges)."""
    from pyabc_tpu.analysis.rules.recorder_rule import Rec001

    path = REPO / "pyabc_tpu" / "observability" / "slo.py"
    src = path.read_text()
    rel = "pyabc_tpu/observability/slo.py"
    open_, _ = check(Rec001(), src, rel)
    assert open_ == [], [f.to_dict() for f in open_]
    mutated = src + (
        "\n\ndef _spill_alert_log(snapshot, path):\n"
        "    import json\n"
        "    with open(path, 'a') as f:\n"
        "        f.write(json.dumps(snapshot))\n"
    )
    open_m, _ = check(Rec001(), mutated, rel)
    assert len(open_m) >= 1, (
        "a file write re-added to observability/slo.py left REC001 "
        "silent — the telemetry-persistence confinement contract is "
        "no longer guarded")


def test_registry_has_twelve_rules_with_dist001_and_rec001():
    from pyabc_tpu.analysis.rules import rule_ids

    ids = rule_ids()
    assert len(ids) == 12
    assert "ISO001" in ids
    assert "PLACE001" in ids
    assert "DIST001" in ids
    assert "REC001" in ids


# ------------------------------------------------------- the tier-1 gate

def test_repo_is_lint_clean():
    """abc-lint over the whole default scan set: zero unbaselined
    findings, no stale baseline entries, every suppression/baseline
    entry carries a reason (enforced at parse/load time)."""
    targets = [REPO / t for t in DEFAULT_TARGETS]
    files = iter_python_files([t for t in targets if t.exists()])
    res = run_analysis(REPO, files, all_rules())
    entries = baseline.load(REPO / baseline.DEFAULT_BASELINE_NAME)
    baseline.apply(res, entries)
    assert res.open == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in res.open)
    assert res.stale_baseline == [], res.stale_baseline
    assert all(f.reason for f in res.suppressed + res.baselined)
