"""External shell-script model through the full ABC loop
(reference test/external/test_external.py pattern)."""
import os
import stat

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.external import ExternalDistance, ExternalModel

SIM_SH = r"""#!/bin/sh
# contract: $0 --in <params> --out <sumstats>
while [ $# -gt 0 ]; do
  case "$1" in
    --in) IN="$2"; shift 2;;
    --out) OUT="$2"; shift 2;;
    *) shift;;
  esac
done
MU=$(awk '$1=="mu"{print $2}' "$IN")
# deterministic "simulator": y = mu, z = 2*mu
awk -v mu="$MU" 'BEGIN{printf "y %s\nz %s\n", mu, 2*mu}' > "$OUT"
"""

DIST_SH = r"""#!/bin/sh
while [ $# -gt 0 ]; do
  case "$1" in
    --in) X="$2"; shift 2;;
    --in0) X0="$2"; shift 2;;
    --out) OUT="$2"; shift 2;;
    *) shift;;
  esac
done
Y=$(awk '$1=="y"{print $2}' "$X"); Y0=$(awk '$1=="y"{print $2}' "$X0")
awk -v a="$Y" -v b="$Y0" 'BEGIN{d=a-b; if (d<0) d=-d; printf "distance %s\n", d}' > "$OUT"
"""


def _write_script(tmp_path, name, body):
    path = str(tmp_path / name)
    with open(path, "w") as fh:
        fh.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path


def test_external_model_full_loop(tmp_path):
    sim = _write_script(tmp_path, "sim.sh", SIM_SH)
    model = ExternalModel("/bin/sh", script=sim)
    # direct contract check
    out = model.sample({"mu": 0.5})
    assert out["y"] == pytest.approx(0.5)
    assert out["z"] == pytest.approx(1.0)

    prior = pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0))
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                    population_size=40,
                    eps=pt.ListEpsilon([1.0, 0.4]),
                    sampler=pt.SingleCoreSampler())
    assert not abc._device_capable  # external models force the host path
    abc.new("sqlite://", {"y": 1.0, "z": 2.0})
    np.random.seed(4)
    h = abc.run(max_nr_populations=2)
    df, w = h.get_distribution(0)
    mu = float(np.sum(df["mu"] * w))
    # deterministic sim: posterior concentrates on mu within final eps of 1.0
    assert abs(mu - 1.0) < 0.3


def test_external_distance(tmp_path):
    sim = _write_script(tmp_path, "sim.sh", SIM_SH)
    dist = _write_script(tmp_path, "dist.sh", DIST_SH)
    model = ExternalModel("/bin/sh", script=sim)
    d = ExternalDistance("/bin/sh", script=dist)
    assert d({"y": 3.0}, {"y": 1.0}) == pytest.approx(2.0)

    prior = pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0))
    abc = pt.ABCSMC(model, prior, d, population_size=20,
                    eps=pt.ListEpsilon([1.0]),
                    sampler=pt.SingleCoreSampler())
    abc.new("sqlite://", {"y": 1.0, "z": 2.0})
    np.random.seed(5)
    h = abc.run(max_nr_populations=1)
    assert h.n_populations == 1


def test_external_model_error_propagates(tmp_path):
    bad = _write_script(tmp_path, "bad.sh", "#!/bin/sh\nexit 3\n")
    model = ExternalModel("/bin/sh", script=bad)
    with pytest.raises(RuntimeError, match="rc=3"):
        model.sample({"mu": 0.0})
