"""Gated adapter tests: Dask sampler, R/Julia models, PEtab importer.

The optional backends (distributed, Rscript, julia) are absent in this
environment; the contract under test is (a) informative gating errors, (b)
full functionality when the backend IS present (skipif-guarded, mirroring
the reference's skipif-missing-R pattern), and (c) the PEtab importer,
which is dependency-light and fully testable from fixture files.
"""
import shutil
import textwrap

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.petab import PetabProblem

HAS_DASK = False
try:
    import distributed  # noqa: F401

    HAS_DASK = True
except ImportError:
    pass
HAS_R = shutil.which("Rscript") is not None
HAS_JULIA = shutil.which("julia") is not None


# ------------------------------------------------------------------- gating

@pytest.mark.skipif(HAS_DASK, reason="distributed installed")
def test_dask_sampler_gating():
    from pyabc_tpu.sampler import DaskDistributedSampler

    with pytest.raises(ImportError, match="distributed"):
        DaskDistributedSampler(dask_client=object())


@pytest.mark.skipif(HAS_R, reason="Rscript installed")
def test_r_adapter_gating(tmp_path):
    from pyabc_tpu.external import R

    with pytest.raises(RuntimeError, match="Rscript"):
        R(str(tmp_path / "model.R"))


@pytest.mark.skipif(HAS_JULIA, reason="julia installed")
def test_julia_adapter_gating(tmp_path):
    from pyabc_tpu.external import JuliaModel

    with pytest.raises(RuntimeError, match="julia"):
        JuliaModel(str(tmp_path / "model.jl"))


# ------------------------------------------- functional (when available)

@pytest.mark.skipif(not HAS_R, reason="needs Rscript")
def test_r_model_runs(tmp_path):
    from pyabc_tpu.external import R

    script = tmp_path / "model.R"
    script.write_text(textwrap.dedent("""
        myModel <- function(pars) list(x = pars$theta * 2)
        mySumStatData <- list(x = 1.0)
    """))
    r = R(str(script))
    out = r.model().sample(pt.Parameter({"theta": 3.0}))
    assert float(out["x"][0]) == pytest.approx(6.0)
    obs = r.observation()
    assert float(obs["x"][0]) == pytest.approx(1.0)


@pytest.mark.skipif(not HAS_DASK, reason="needs distributed")
def test_dask_sampler_runs():  # pragma: no cover - needs a live cluster
    from distributed import Client, LocalCluster

    from pyabc_tpu.sampler import DaskDistributedSampler

    with LocalCluster(n_workers=2, processes=False) as cluster:
        sampler = DaskDistributedSampler(Client(cluster))
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        model = pt.SimpleModel(
            lambda p: {"x": p["theta"]}, name="m"
        )
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=50,
                        eps=pt.ListEpsilon([1.0, 0.5]), sampler=sampler)
        abc.new("sqlite://", {"x": 0.5})
        h = abc.run(max_nr_populations=2)
        assert h.n_populations == 2


# --------------------------------------------------------------- PEtab

@pytest.fixture
def petab_dir(tmp_path):
    (tmp_path / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "nominalValue\tobjectivePriorType\tobjectivePriorParameters\n"
        "k1\tlog10\t0.01\t100\t1\t1.0\t\t\n"
        "k2\tlin\t0\t10\t1\t5.0\tparameterScaleNormal\t5;2\n"
        "k3\tlin\t0\t1\t0\t0.3\t\t\n"
    )
    (tmp_path / "measurements.tsv").write_text(
        "observableId\tsimulationConditionId\tmeasurement\ttime\n"
        "obs_a\tc0\t1.5\t2.0\n"
        "obs_a\tc0\t0.7\t1.0\n"
        "obs_b\tc0\t3.0\t1.0\n"
    )
    (tmp_path / "problem.yaml").write_text(textwrap.dedent("""
        format_version: 1
        parameter_file: parameters.tsv
        problems:
          - measurement_files: [measurements.tsv]
    """))
    return tmp_path


def test_petab_prior_and_data(petab_dir):
    prob = PetabProblem.from_yaml(str(petab_dir / "problem.yaml"))
    prior = prob.prior()
    assert set(prior.space.names) == {"k1", "k2"}
    # k1: parameterScaleUniform on log10 scale over [-2, 2]
    par = prior.rvs_host()
    assert -2.0 <= par["k1"] <= 2.0
    # logpdf of k1 uniform: 1/4 over the scaled bounds
    import scipy.stats

    samples = np.asarray([prior.rvs_host()["k1"] for _ in range(200)])
    assert samples.min() >= -2.0 and samples.max() <= 2.0
    # k2: normal(5, 2)
    k2s = np.asarray([prior.rvs_host()["k2"] for _ in range(500)])
    assert abs(k2s.mean() - 5.0) < 0.4
    # fixed parameter on its scale
    assert prob.nominal_parameters() == {"k3": pytest.approx(0.3)}
    # measurements grouped + time-ordered
    obs = prob.observed_data()
    np.testing.assert_allclose(obs["obs_a"], [0.7, 1.5])
    np.testing.assert_allclose(obs["obs_b"], [3.0])
    times = prob.observation_times()
    np.testing.assert_allclose(times["obs_a"], [1.0, 2.0])


def test_petab_unsupported_prior(petab_dir):
    (petab_dir / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "nominalValue\tobjectivePriorType\tobjectivePriorParameters\n"
        "k1\tlog10\t0.01\t100\t1\t1.0\tnormal\t1;2\n"
    )
    prob = PetabProblem.from_yaml(str(petab_dir / "problem.yaml"))
    with pytest.raises(ValueError, match="not representable"):
        prob.prior()


def test_petab_linear_uniform_on_log_scale_rejected(petab_dir):
    """A linear-scale flat prior on a log-scaled parameter is a DIFFERENT
    distribution after the transform (Jacobian 1/x); the importer must
    refuse rather than silently bias the posterior."""
    (petab_dir / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "nominalValue\tobjectivePriorType\tobjectivePriorParameters\n"
        "k1\tlog10\t0.01\t100\t1\t1.0\tuniform\t1;100\n"
    )
    prob = PetabProblem.from_yaml(str(petab_dir / "problem.yaml"))
    with pytest.raises(ValueError, match="not representable"):
        prob.prior()


def test_petab_lognormal_prior(petab_dir):
    """logNormal (mean, sd of log X) maps to the scipy lognorm convention
    (s=sd, scale=exp(mean)); E[log X] must come out at `mean`."""
    (petab_dir / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "nominalValue\tobjectivePriorType\tobjectivePriorParameters\n"
        "k1\tlin\t0.001\t100\t1\t1.0\tlogNormal\t0.5;0.25\n"
    )
    prob = PetabProblem.from_yaml(str(petab_dir / "problem.yaml"))
    prior = prob.prior()
    logs = np.log([prior.rvs_host()["k1"] for _ in range(800)])
    assert logs.mean() == pytest.approx(0.5, abs=0.05)
    assert logs.std() == pytest.approx(0.25, abs=0.04)


# --------------------------------------------------------------- COPASI

HAS_BASICO = False
try:
    import basico  # noqa: F401

    HAS_BASICO = True
except ImportError:
    pass


@pytest.mark.skipif(HAS_BASICO, reason="basico installed")
def test_copasi_basico_gating(tmp_path):
    from pyabc_tpu.copasi import BasicoModel

    with pytest.raises(ImportError, match="basico"):
        BasicoModel(str(tmp_path / "model.cps"))


@pytest.mark.skipif(not HAS_BASICO, reason="needs basico")
def test_copasi_basico_runs(tmp_path):  # pragma: no cover - needs basico
    from pyabc_tpu.copasi import BasicoModel

    import basico

    dm = basico.new_model(name="decay")
    basico.add_reaction("decay", "A ->")
    basico.set_species("A", initial_concentration=10.0)
    path = str(tmp_path / "decay.cps")
    basico.save_model(path, model=dm)
    model = BasicoModel(path, duration=1.0, n_points=5)
    out = model.sample(pt.Parameter({"(decay).k1": 0.5}))
    assert any(len(v) == 5 for v in out.values())
