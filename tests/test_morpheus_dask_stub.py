"""Stub/mock execution tests for the Morpheus adapter and the Dask sampler.

Same philosophy as ``test_adapters_stub.py``: a fake ``morpheus`` binary
exercises the XML parameter-substitution + CLI + logger-CSV contract, and
a mock ``distributed`` module (Client.get_executor -> a real
ThreadPoolExecutor) drives DaskDistributedSampler's delegation loop with
actual concurrent futures.
"""
import os
import stat
import sys
import textwrap
import types
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import pyabc_tpu as pt

MORPHEUS_STUB = textwrap.dedent("""\
    #!{python}
    import sys
    import xml.etree.ElementTree as ET
    args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
    root = ET.parse(args["-file"]).getroot()
    k = float(root.find("./Global/Constant[@symbol='k']").get("value"))
    with open(args["-outdir"] + "/logger.csv", "w") as fh:
        fh.write("time,cells\\n")
        for t in range(4):
            fh.write("%d,%r\\n" % (t, k * t))
""")

MODEL_XML = """<MorpheusModel>
  <Global>
    <Constant symbol="k" value="1.0"/>
    <Constant symbol="other" value="7.0"/>
  </Global>
</MorpheusModel>
"""


@pytest.fixture
def fake_morpheus(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    p = bindir / "morpheus"
    p.write_text(MORPHEUS_STUB.format(python=sys.executable))
    p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    model = tmp_path / "model.xml"
    model.write_text(MODEL_XML)
    return model


class TestMorpheusAdapter:
    def test_parameter_substitution_and_output(self, fake_morpheus):
        from pyabc_tpu.external import MorpheusModel

        model = MorpheusModel(
            str(fake_morpheus),
            par_map={"k": "./Global/Constant[@symbol='k']"},
        )
        out = model.sample({"k": 2.5})
        np.testing.assert_allclose(out["cells"], [0.0, 2.5, 5.0, 7.5])
        np.testing.assert_allclose(out["time"], [0, 1, 2, 3])

    def test_bad_xpath_raises(self, fake_morpheus):
        from pyabc_tpu.external import MorpheusModel

        model = MorpheusModel(
            str(fake_morpheus),
            par_map={"k": "./Global/Constant[@symbol='missing']"},
        )
        with pytest.raises(KeyError, match="matches no element"):
            model.sample({"k": 1.0})

    def test_gated_without_binary(self, tmp_path):
        from pyabc_tpu.external import MorpheusModel

        with pytest.raises(RuntimeError, match="morpheus"):
            MorpheusModel(str(tmp_path / "m.xml"), par_map={},
                          executable="definitely-not-morpheus")


class TestDaskSamplerWithMockDistributed:
    def test_delegation_runs_real_futures(self, monkeypatch):
        executor = ThreadPoolExecutor(max_workers=4)

        class _Client:
            def get_executor(self):
                return executor

            def close(self):
                executor.shutdown(wait=False)

        mod = types.ModuleType("distributed")
        mod.Client = _Client
        monkeypatch.setitem(sys.modules, "distributed", mod)
        from pyabc_tpu.sampler.dask_sampler import DaskDistributedSampler

        sampler = DaskDistributedSampler(dask_client=_Client(),
                                         batch_size=4)

        def sim(pars):
            return {"x": pars["theta"] + 0.5 * np.random.normal()}

        model = pt.SimpleModel(sim, name="g")
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=60,
                        eps=pt.QuantileEpsilon(initial_epsilon=1.5,
                                               alpha=0.5),
                        sampler=sampler, seed=4)
        abc.new("sqlite://", {"x": 1.0})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations == 3
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(0.8, abs=0.35)
        assert sampler.nr_evaluations_ > 0
        sampler.stop()
