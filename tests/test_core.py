"""Unit tests for core primitives (reference analog: test_weighted_statistics,
parts of test_random_variables / test_population)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pyabc_tpu.core import (
    RV,
    Distribution,
    LowerBoundDecorator,
    Parameter,
    ParameterSpace,
    Particle,
    Population,
    SumStatSpec,
    effective_sample_size,
    weighted_mean,
    weighted_median,
    weighted_quantile,
    weighted_std,
)
from pyabc_tpu.ops import stats as ops_stats


class TestWeightedStatistics:
    def test_quantile_uniform_weights(self):
        pts = np.arange(10.0)
        assert weighted_quantile(pts, alpha=0.5) == pytest.approx(4.0)

    def test_quantile_respects_weights(self):
        pts = np.array([0.0, 1.0])
        w = np.array([0.1, 0.9])
        assert weighted_quantile(pts, w, alpha=0.5) == 1.0
        w = np.array([0.9, 0.1])
        assert weighted_quantile(pts, w, alpha=0.5) == 0.0

    def test_median_mean_std(self):
        pts = np.array([1.0, 2.0, 3.0, 4.0])
        w = np.array([1.0, 1.0, 1.0, 1.0])
        assert weighted_median(pts, w) == pytest.approx(2.0)
        assert weighted_mean(pts, w) == pytest.approx(2.5)
        assert weighted_std(pts, w) == pytest.approx(np.std(pts))

    def test_ess(self):
        assert effective_sample_size(np.ones(100)) == pytest.approx(100.0)
        w = np.zeros(100)
        w[0] = 1.0
        assert effective_sample_size(w) == pytest.approx(1.0)

    def test_device_quantile_matches_host(self):
        rng = np.random.default_rng(0)
        pts = rng.normal(size=257)
        w = rng.uniform(0.1, 1.0, size=257)
        for alpha in [0.1, 0.5, 0.9]:
            host = weighted_quantile(pts, w, alpha)
            dev = float(
                ops_stats.weighted_quantile(
                    jnp.asarray(pts), jnp.asarray(w), alpha
                )
            )
            assert host == pytest.approx(dev, rel=1e-5)


class TestRV:
    @pytest.mark.parametrize(
        "rv,scipy_name,scipy_args",
        [
            (RV("uniform", 1.0, 3.0), "uniform", (1.0, 3.0)),
            (RV("norm", 2.0, 0.5), "norm", (2.0, 0.5)),
            (RV("expon", 0.0, 2.0), "expon", (0.0, 2.0)),
            (RV("gamma", 3.0, 0.0, 2.0), "gamma", (3.0, 0.0, 2.0)),
            (RV("beta", 2.0, 5.0), "beta", (2.0, 5.0)),
            (RV("laplace", 0.0, 1.5), "laplace", (0.0, 1.5)),
            (RV("lognorm", 0.5, 0.0, 2.0), "lognorm", (0.5, 0.0, 2.0)),
        ],
    )
    def test_logpdf_matches_scipy(self, rv, scipy_name, scipy_args):
        import scipy.stats as st

        frozen = getattr(st, scipy_name)(*scipy_args)
        xs = np.asarray(frozen.rvs(size=50, random_state=1), dtype=np.float64)
        ours = np.asarray(jax.vmap(rv.logpdf)(jnp.asarray(xs, jnp.float32)))
        theirs = frozen.logpdf(xs)
        np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-3)

    def test_sampling_moments(self):
        key = jax.random.key(0)
        x = np.asarray(RV("norm", 2.0, 0.5).rvs(key, (20000,)))
        assert x.mean() == pytest.approx(2.0, abs=0.02)
        assert x.std() == pytest.approx(0.5, abs=0.02)

    def test_uniform_support(self):
        rv = RV("uniform", 1.0, 3.0)
        assert float(rv.logpdf(0.5)) == -np.inf
        assert float(rv.logpdf(2.0)) == pytest.approx(-np.log(3.0), rel=1e-3)
        assert float(rv.logpdf(4.5)) == -np.inf

    def test_discrete_randint(self):
        rv = RV("randint", 0, 4)
        assert rv.discrete
        x = np.asarray(rv.rvs(jax.random.key(1), (1000,)))
        assert set(np.unique(x)) <= {0, 1, 2, 3}
        assert float(rv.logpdf(2)) == pytest.approx(-np.log(4.0), rel=1e-3)
        assert float(rv.logpdf(7)) == -np.inf

    def test_poisson_binom_pmfs(self):
        import scipy.stats as st

        pois = RV("poisson", 3.5)
        xs = np.arange(10)
        np.testing.assert_allclose(
            np.asarray(jax.vmap(pois.logpdf)(jnp.asarray(xs))),
            st.poisson(3.5).logpmf(xs), rtol=1e-4, atol=1e-4,
        )
        binom = RV("binom", 10, 0.3)
        np.testing.assert_allclose(
            np.asarray(jax.vmap(binom.logpdf)(jnp.asarray(xs))),
            st.binom(10, 0.3).logpmf(xs), rtol=1e-3, atol=1e-3,
        )

    def test_lower_bound_decorator(self):
        rv = LowerBoundDecorator(RV("norm", 0.0, 1.0), 0.0)
        x = np.asarray(rv.rvs(jax.random.key(0), (1000,)))
        assert (x > 0).all()
        assert float(rv.logpdf(-1.0)) == -np.inf
        assert np.isfinite(float(rv.logpdf(1.0)))


class TestDistribution:
    def test_rvs_and_pdf(self):
        dist = Distribution(a=RV("uniform", 0.0, 1.0), b=RV("norm", 0.0, 2.0))
        par = dist.rvs(jax.random.key(0))
        assert isinstance(par, Parameter)
        assert set(par) == {"a", "b"}
        import scipy.stats as st

        expected = st.uniform(0, 1).pdf(par["a"]) * st.norm(0, 2).pdf(par["b"])
        assert dist.pdf(par) == pytest.approx(expected, rel=1e-4)

    def test_dense_roundtrip(self):
        dist = Distribution(x=RV("norm", 1.0, 1.0), y=RV("uniform", -1.0, 2.0))
        theta = dist.rvs_array(jax.random.key(3))
        assert theta.shape == (2,)
        lp = dist.logpdf_array(theta)
        assert np.isfinite(float(lp))
        # padded theta reads only the first dim columns
        padded = jnp.concatenate([theta, jnp.zeros(3)])
        assert float(dist.logpdf_array(padded)) == pytest.approx(float(lp))

    def test_batched_logpdf(self):
        dist = Distribution(x=RV("norm", 0.0, 1.0))
        thetas = jnp.linspace(-2, 2, 11)[:, None]
        lps = dist.logpdf_array(thetas)
        assert lps.shape == (11,)


class TestPopulation:
    def _make(self):
        spaces = [ParameterSpace(["a", "b"]), ParameterSpace(["c"])]
        spec = SumStatSpec({"s": np.zeros(3)})
        particles = [
            Particle(0, Parameter(a=1.0, b=2.0), 0.3, {"s": np.ones(3)}, 0.5),
            Particle(0, Parameter(a=2.0, b=3.0), 0.3, {"s": np.ones(3)}, 0.2),
            Particle(1, Parameter(c=5.0), 0.4, {"s": np.zeros(3)}, 0.1),
        ]
        return Population.from_particles(particles, spaces, spec)

    def test_normalization_and_model_probs(self):
        pop = self._make()
        assert pop.weights.sum() == pytest.approx(1.0)
        probs = pop.get_model_probabilities()
        assert probs.loc[0, "p"] == pytest.approx(0.6)
        assert probs.loc[1, "p"] == pytest.approx(0.4)
        assert pop.get_alive_models() == [0, 1]

    def test_get_distribution(self):
        pop = self._make()
        df, w = pop.get_distribution(0)
        assert list(df.columns) == ["a", "b"]
        assert len(df) == 2
        assert w.sum() == pytest.approx(1.0)
        df1, w1 = pop.get_distribution(1)
        assert list(df1.columns) == ["c"]
        assert w1.sum() == pytest.approx(1.0)

    def test_weighted_distances(self):
        pop = self._make()
        wd = pop.get_weighted_distances()
        assert set(wd.columns) == {"distance", "w"}
        assert wd["w"].sum() == pytest.approx(1.0)

    def test_particle_roundtrip(self):
        pop = self._make()
        parts = pop.particles()
        assert parts[0].parameter == Parameter(a=1.0, b=2.0)
        assert parts[2].parameter == Parameter(c=5.0)
        assert parts[2].m == 1


class TestSumStatSpec:
    def test_flatten_roundtrip(self):
        spec = SumStatSpec({"a": np.zeros((2, 2)), "b": 0.0, "c": np.zeros(3)})
        assert spec.total_size == 8
        stats = {"a": np.arange(4.0).reshape(2, 2), "b": 7.0, "c": np.ones(3)}
        flat = np.asarray(spec.flatten(stats))
        back = spec.unflatten(flat)
        np.testing.assert_allclose(back["a"], stats["a"])
        assert back["b"] == pytest.approx(7.0)
        np.testing.assert_allclose(back["c"], stats["c"])

    def test_labels(self):
        spec = SumStatSpec({"x": 0.0, "y": np.zeros(2)})
        assert spec.labels() == ["x", "y[0]", "y[1]"]


def test_fast_random_choice_distribution():
    """fast_random_choice (reference pyabc/random_choice.py) must sample
    the given weights for both the small-n scan and large-n searchsorted
    branches."""
    import pyabc_tpu as pt

    np.random.seed(0)
    for n in (3, 40):  # straddles the small-n cutoff
        w = np.random.uniform(0.1, 1.0, n)
        w /= w.sum()
        draws = np.bincount(
            [pt.fast_random_choice(w) for _ in range(20000)], minlength=n
        ) / 20000
        np.testing.assert_allclose(draws, w, atol=0.02)


def test_set_figure_params_roundtrip():
    import matplotlib as mpl

    import pyabc_tpu as pt

    pt.set_figure_params("pyabc", color_map="plasma")
    assert mpl.rcParams["image.cmap"] == "plasma"
    assert mpl.rcParams["axes.spines.top"] is False
    pt.set_figure_params("default")
    import pytest

    with pytest.raises(ValueError, match="unknown theme"):
        pt.set_figure_params("nope")
