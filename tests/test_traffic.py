"""Fleet-scale traffic subsystem (round 19): the open-loop generator.

Layered like the subsystem itself:

1. SPEC ZOO — every traffic class instantiates a VALID TenantSpec
   (admission's validate() accepts it), draws are deterministic in the
   seed, and the weighted class draw respects the profile mix.
2. SCHEDULE — Poisson and burst arrival processes are precomputed and
   seeded: the same seed yields the same arrivals regardless of how
   the scheduler behaves (the open-loop property).
3. GENERATOR LOGIC — on a VirtualClock against a model scheduler: 429s
   are retried exactly Retry-After later (never earlier), non-retryable
   rejections drop, the retry budget bounds loops, and the report's
   honesty ratio is computed from first-hint to eventual admission.
4. RETRY-AFTER HONESTY UNDER CHURN — the property test: a seeded
   arrival storm against the REAL AdmissionController on the injected
   clock, with a capacity loss (device loss) mid-schedule; every hint
   must stay within the documented honesty factor of the wait a
   hint-honoring client actually observes.
5. END-TO-END — a small live fleet on the real scheduler completes and
   the report carries admission latency + time-to-posterior samples.
"""
import numpy as np
import pytest

from pyabc_tpu.observability import VirtualClock
from pyabc_tpu.serving import COMPLETED, RunScheduler
from pyabc_tpu.serving.admission import (
    AdmissionController,
    AdmissionRejectedError,
)
from pyabc_tpu.traffic import (
    ArrivalSchedule,
    TrafficGenerator,
    percentile,
    spec_zoo,
)
from pyabc_tpu.traffic.specs import SPEC_PROFILES, draw_class, make_spec
from pyabc_tpu.utils.bench_defaults import TRAFFIC_HONESTY_P90_MAX


# ============================================================= spec zoo
def test_every_traffic_class_yields_valid_spec():
    from pyabc_tpu.storage.columnar import has_pyarrow

    for profile, classes in SPEC_PROFILES.items():
        for cls in classes:
            if cls.store == "columnar" and not has_pyarrow():
                # the admission gate rejects columnar specs on a host
                # without pyarrow (its own test in test_serving); the
                # zoo's columnar class is only servable with the extra
                continue
            for seed in (0, 7, 123):
                spec = make_spec(cls, seed=seed)
                spec.validate()  # the admission gate must accept it
                assert spec.population_size in cls.pops
                assert spec.generations in cls.gens
                assert spec.store == cls.store


def test_make_spec_deterministic_in_seed():
    cls = spec_zoo("full")[0]
    a, b = make_spec(cls, seed=42), make_spec(cls, seed=42)
    assert a == b
    assert make_spec(cls, seed=43).seed != a.seed


def test_unknown_profile_and_model_rejected():
    from pyabc_tpu.traffic.specs import TrafficClass

    with pytest.raises(ValueError, match="unknown traffic profile"):
        spec_zoo("nope")
    with pytest.raises(ValueError, match="unknown model"):
        TrafficClass("bad", "no-such-model", weight=1.0,
                     pops=(10,), gens=(2,))


def test_draw_class_respects_weights():
    classes = spec_zoo("smoke")
    rng = np.random.default_rng(0)
    names = [draw_class(classes, rng).name for _ in range(2000)]
    counts = {c.name: names.count(c.name) for c in classes}
    # gauss-small carries weight 4/9 of the smoke mix
    assert counts["gauss-small"] > counts["bd-small"]
    assert all(v > 0 for v in counts.values())


# ============================================================= schedule
def test_poisson_schedule_seeded_and_sorted():
    a = ArrivalSchedule.poisson(50, rate_hz=10.0, seed=3)
    b = ArrivalSchedule.poisson(50, rate_hz=10.0, seed=3)
    assert len(a) == 50
    assert [x.due_s for x in a.arrivals] == [x.due_s for x in b.arrivals]
    assert [x.cls.name for x in a.arrivals] == \
        [x.cls.name for x in b.arrivals]
    assert all(x.due_s <= y.due_s for x, y in
               zip(a.arrivals, a.arrivals[1:]))
    c = ArrivalSchedule.poisson(50, rate_hz=10.0, seed=4)
    assert [x.due_s for x in c.arrivals] != [x.due_s for x in a.arrivals]


def test_burst_schedule_shape():
    s = ArrivalSchedule.burst(3, burst_size=5, interval_s=2.0, seed=1)
    assert len(s) == 15 and s.horizon_s == 4.0
    due = [x.due_s for x in s.arrivals]
    assert due.count(0.0) == 5 and due.count(2.0) == 5


def test_percentile_of_empty_is_nan():
    assert np.isnan(percentile([], 99))
    # round 22: percentile() rides the shared Histogram.quantile log2-
    # bucket estimator (bucket upper edge capped at the observed max) —
    # a CONSERVATIVE estimate, never below the true percentile and
    # never above the largest sample
    p50 = percentile([1.0, 2.0, 3.0], 50)
    assert 2.0 <= p50 <= 3.0
    # a clear bucket separation resolves exactly: 99 fast samples, one
    # slow outlier — p50 must not be dragged to the outlier
    p50 = percentile([0.5] * 99 + [40.0], 50)
    assert 0.5 <= p50 < 1.1
    assert percentile([0.5] * 99 + [40.0], 100) == 40.0


# ==================================================== generator (model)
class ModelScheduler:
    """A capacity-k scheduler model on a VirtualClock: real
    AdmissionController pricing, fake tenants that 'complete' after a
    fixed service time — enough to exercise every generator path
    without jax."""

    class _Tenant:
        def __init__(self, tid, now, service_s):
            self.id = tid
            self.state = "running"
            self.submitted_at = now
            self.finished_at = None
            self._done_at = now + service_s

        def tick(self, now):
            if self.state == "running" and now >= self._done_at:
                self.state = "completed"
                self.finished_at = self._done_at

    def __init__(self, clock, capacity=2, max_queued=2, service_s=8.0):
        self.clock = clock
        self.capacity = capacity
        self.service_s = service_s
        self.admission = AdmissionController(
            max_queued=max_queued, n_chips=capacity, clock=clock)
        self._live: dict = {}
        self._n = 0

    def _pump(self):
        now = self.clock.now()
        for t in self._live.values():
            t.tick(now)

    def submit(self, spec):
        self._pump()
        running = [t for t in self._live.values()
                   if t.state == "running"]
        # model: capacity slots run, the rest of 'running' is the queue
        queued = max(0, len(running) - self.capacity)
        if len(running) >= self.capacity + self.admission.max_queued:
            self.admission.admit(spec, queued_now=self.admission.max_queued,
                                 live_now=len(running))
        self._n += 1
        tid = f"m{self._n}"
        # queue position delays the start: FIFO behind current work
        delay = (len(running) // self.capacity) * self.service_s
        t = self._Tenant(tid, self.clock.now(),
                         delay + self.service_s)
        self._live[tid] = t
        self.admission.note_run_seconds(self.service_s)
        return t

    def get(self, tid):
        self._pump()
        return self._live.get(tid)

    def cancel(self, tid):
        t = self._live.get(tid)
        if t is None or t.state != "running":
            return False
        t.state = "cancelled"
        t.finished_at = self.clock.now()
        return True


def _drive(gen, clock, horizon_s, dt=0.5):
    for _ in range(int(horizon_s / dt)):
        gen.step()
        if gen.done():
            break
        clock.advance(dt)
    gen.step()


def test_generator_open_loop_retries_honor_retry_after():
    clock = VirtualClock()
    sched = ModelScheduler(clock, capacity=1, max_queued=1,
                           service_s=10.0)
    schedule = ArrivalSchedule.burst(1, burst_size=6, interval_s=1.0,
                                     seed=5)
    gen = TrafficGenerator(sched, schedule)
    _drive(gen, clock, horizon_s=600.0)
    assert gen.done()
    rep = gen.report()
    assert rep["submitted"] == 6  # every arrival eventually admitted
    assert rep["rejections"] > 0  # the burst overflowed the queue
    assert rep["dropped"] == 0
    assert rep["states"].get("completed") == 6
    # honesty samples exist and a hint-honoring client's observed wait
    # is never SHORTER than the hint (we retry exactly at the hint)
    assert rep["honesty_ratio"]["n"] == len(
        [a for a in gen._done if a.first_hint_s])
    assert rep["honesty_ratio"]["p50"] >= 1.0


def test_generator_drops_non_retryable_and_bounds_retries():
    clock = VirtualClock()

    class AlwaysReject:
        def __init__(self, hint):
            self.clock = clock
            self.hint = hint

        def submit(self, spec):
            raise AdmissionRejectedError("no", retry_after_s=self.hint)

        def get(self, tid):
            return None

    # non-retryable (hint None): dropped on first contact
    gen = TrafficGenerator(AlwaysReject(None),
                           ArrivalSchedule.poisson(3, 10.0, seed=1))
    _drive(gen, clock, horizon_s=10.0)
    rep = gen.report()
    assert rep["dropped"] == 3 and rep["states"] == {"dropped": 3}

    # retryable but never admitted: the retry budget ends the loop
    gen = TrafficGenerator(AlwaysReject(1.0),
                           ArrivalSchedule.poisson(2, 10.0, seed=1),
                           max_retries=5)
    _drive(gen, clock, horizon_s=60.0)
    rep = gen.report()
    assert gen.done()
    assert rep["dropped"] == 2
    assert rep["rejections"] == 2 * (5 + 1)


def test_generator_counts_arrivals_and_rejections_in_metrics():
    from pyabc_tpu.observability import MetricsRegistry
    from pyabc_tpu.observability.metrics import (
        TRAFFIC_ARRIVALS_TOTAL,
        TRAFFIC_REJECTIONS_TOTAL,
    )

    clock = VirtualClock()
    sched = ModelScheduler(clock, capacity=1, max_queued=1,
                           service_s=5.0)
    reg = MetricsRegistry(clock=clock)
    gen = TrafficGenerator(
        sched, ArrivalSchedule.burst(1, 4, 1.0, seed=2), metrics=reg)
    _drive(gen, clock, horizon_s=300.0)
    snap = reg.snapshot()
    assert snap[TRAFFIC_ARRIVALS_TOTAL] >= 4
    assert snap[TRAFFIC_REJECTIONS_TOTAL] == gen.report()["rejections"]


def test_generator_abort_pending_quiesces():
    """Phase boundaries in the bench lane: abort_pending drops every
    unfired retry and cancels the live tenants, after which done() is
    immediate (cancelled is terminal)."""
    clock = VirtualClock()
    sched = ModelScheduler(clock, capacity=1, max_queued=1,
                           service_s=50.0)
    gen = TrafficGenerator(sched, ArrivalSchedule.burst(1, 6, 1.0,
                                                        seed=9))
    gen.step()  # burst: 2 admitted (slot+queue), 4 heaped as retries
    assert gen._pending and gen._heap
    n = gen.abort_pending()
    assert n == 2  # slot + queue occupants both cancelled
    assert gen._pending == {} and gen._heap == [] and gen.done()
    states = gen.report()["states"]
    assert states.get("cancelled") == 2


# ============================== Retry-After honesty property (churn)
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_retry_after_honesty_under_churn_with_device_loss(seed):
    """Property: a finite arrival storm against the real
    AdmissionController, WITH a capacity loss (device loss) while the
    backlog drains — a client that honors Retry-After observes a wait
    within the documented honesty factor of the first hint, and never
    admits before the hint elapses. (Under SUSTAINED open-loop
    overload the first hint legitimately underestimates — new arrivals
    keep refilling the queue it priced — which is exactly why the
    bench bound is loose; the property proper is about the promised
    DRAIN, so the storm here is a burst that then drains.)"""
    rng = np.random.default_rng(seed)
    burst = int(10 + rng.integers(0, 5))
    clock = VirtualClock()
    sched = ModelScheduler(clock, capacity=4, max_queued=2,
                           service_s=6.0)
    schedule = ArrivalSchedule.burst(1, burst_size=burst,
                                     interval_s=1.0, seed=seed)
    gen = TrafficGenerator(sched, schedule)
    lost = False
    for _ in range(4000):
        gen.step()
        if gen.done():
            break
        # device loss mid-drain: half the pool vanishes, the
        # controller reprices every subsequent hint on 2 chips
        if not lost and clock.now() > 3.0:
            sched.capacity = 2
            sched.admission.set_capacity(2)
            lost = True
        clock.advance(0.25)
    gen.step()
    rep = gen.report()
    assert lost and gen.done()
    assert rep["rejections"] > 0, "storm never hit the queue bound"
    assert rep["dropped"] == 0
    hr = rep["honesty_ratio"]
    assert hr["n"] > 0
    assert hr["p50"] >= 1.0  # never admitted before the hint
    assert hr["max"] <= TRAFFIC_HONESTY_P90_MAX, hr


# ============================================================ end to end
@pytest.mark.slow
def test_generator_live_fleet_completes_and_reports(tmp_path):
    """A small real fleet (gaussian-only schedule, one compiled shape)
    through the actual RunScheduler: everything admits, completes, and
    the report carries real latency + time-to-posterior samples."""
    from pyabc_tpu.traffic.generator import Arrival
    from pyabc_tpu.traffic.specs import TrafficClass

    cls = TrafficClass("gauss-tiny", "gaussian", weight=1.0,
                       pops=(60,), gens=(2,), fused_generations=2)
    schedule = ArrivalSchedule([
        Arrival(idx=i, due_s=0.2 * i, cls=cls, seed=900 + i)
        for i in range(3)
    ])
    sched = RunScheduler(n_slots=2, max_queued=8,
                         base_dir=str(tmp_path / "serve"),
                         lifecycle_sweep_s=0.5)
    try:
        gen = TrafficGenerator(sched, schedule)
        gen.run(budget_s=240.0, poll_s=0.05)
        rep = gen.report()
        assert rep["states"].get(COMPLETED) == 3, rep["states"]
        assert rep["admission_latency_s"]["n"] == 3
        assert rep["time_to_posterior_s"]["n"] == 3
        assert rep["time_to_posterior_s"]["p99"] > 0
        assert rep["fairness_max_ratio"] >= 1.0
    finally:
        sched.shutdown()
