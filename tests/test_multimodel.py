"""Multi-model inference (model selection) statistical tests — config 5.

Mirrors the reference's model-selection integration test: two analytically
tractable models, posterior model probabilities vs exact Bayes factors
(SURVEY.md §4 'model selection with two analytically tractable models').
"""
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import model_selection as msel

X_OBS = 1.0


class TestTractablePair:
    def test_model_posterior_matches_bayes_factor(self):
        models, priors, analytic = msel.tractable_pair()
        abc = pt.ABCSMC(
            models, priors, pt.PNormDistance(p=2),
            population_size=600,
            eps=pt.MedianEpsilon(),
            seed=7,
        )
        assert abc._device_capable
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=6)
        probs = h.get_model_probabilities(h.max_t)
        expected = analytic(X_OBS)
        # as eps -> 0, p(m | d < eps) -> exact model posterior; tolerate
        # SMC noise at finite eps
        for m in range(2):
            p = float(probs["p"].get(m, 0.0))
            assert p == pytest.approx(expected[m], abs=0.15), (m, p, expected)

    def test_within_model_posterior(self):
        models, priors, _ = msel.tractable_pair()
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=600, seed=8)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=6)
        # theta posterior within model 0: conjugate N with sd 0.6 noise
        sd = 0.6
        post_var = 1.0 / (1.0 + 1.0 / sd**2)
        post_mu = post_var * X_OBS / sd**2
        df, w = h.get_distribution(0)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(post_mu, abs=0.2)

    def test_history_tracks_alive_models(self):
        models, priors, _ = msel.tractable_pair()
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=200, seed=9)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        alive = h.alive_models(h.max_t)
        assert set(alive) <= {0, 1} and len(alive) >= 1
        probs_all = h.get_model_probabilities()
        assert probs_all.shape[0] == h.n_populations


class TestHeterogeneousDims:
    """Models with different parameter dimensionality in one run (exercises
    theta padding + per-branch density normalization)."""

    def test_ode_family_runs(self):
        models, priors, _ = msel.ode_family(n_obs=8, t1=6.0)
        obs = msel.observed_ode_family(seed=3, true_model=1, n_obs=8, t1=6.0)
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=250, seed=10)
        assert abc._device_capable
        abc.new("sqlite://", obs)
        h = abc.run(max_nr_populations=4)
        probs = h.get_model_probabilities(h.max_t)
        assert probs["p"].sum() == pytest.approx(1.0)
        # the 1-param pure-decay model cannot fit the production plateau;
        # it should not dominate
        assert float(probs["p"].get(0, 0.0)) < 0.9
