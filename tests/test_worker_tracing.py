"""Distributed tracing across the broker/worker boundary (round 8).

Worker processes record their own phase spans (connect / wait /
deserialize / simulate / serialize / ship) on an injected clock,
piggyback the summaries on existing result messages, and estimate their
clock offset against the broker NTP-style from stamped request/response
exchanges. The broker ingests, offset-maps and hands the spans to the
sampler as per-worker pseudo-threads; the elastic gap accountant then
decomposes broker-path dark time. Tested here: the offset math under
deliberate clock skew (merged spans must land within the RTT-derived
uncertainty), protocol backward compatibility (pre-tracing workers),
and the end-to-end merge with real worker subprocesses.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.broker.broker import EvalBroker
from pyabc_tpu.broker.worker import (
    WorkerSpanRecorder,
    _broker_stamp,
    run_worker,
)
from pyabc_tpu.observability import (
    ClockOffsetEstimator,
    Tracer,
    VirtualClock,
    elastic_gap_attribution,
    worker_trace_spans,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORKER_CODE = (
    "from pyabc_tpu.broker import run_worker; "
    "import sys; run_worker('127.0.0.1', int(sys.argv[1]))"
)


def _spawn_worker(port):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-c", WORKER_CODE, str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


# ---------------------------------------------------------------- offsets
class _OffsetClock(VirtualClock):
    """A worker clock: the broker's virtual clock plus a fixed skew
    (separate monotonic epochs)."""

    def __init__(self, base: VirtualClock, skew: float):
        self._base = base
        self._skew = float(skew)

    def now(self):
        return self._base.now() + self._skew

    def wall(self):
        return self.now()


def test_offset_estimator_symmetric_exchange_is_exact():
    base = VirtualClock(100.0)
    wclock = _OffsetClock(base, 1000.0)
    est = ClockOffsetEstimator()
    t1 = wclock.now()
    base.advance(0.005)            # request wire latency
    t2 = base.now()                # broker stamps its clock
    base.advance(0.005)            # reply wire latency
    t4 = wclock.now()
    est.add_sample(t1, t2, t4)
    # symmetric latencies: the midpoint assumption is exact
    assert est.offset == pytest.approx(-1000.0, abs=1e-12)
    assert est.uncertainty_s == pytest.approx(0.005)
    assert est.rtt_s == pytest.approx(0.01)
    # mapping round-trips
    assert est.to_local(est.to_remote(42.0)) == pytest.approx(42.0)


def test_offset_estimator_error_bounded_by_uncertainty_under_asymmetry():
    base = VirtualClock(0.0)
    wclock = _OffsetClock(base, -37.5)
    est = ClockOffsetEstimator()
    # pathologically asymmetric exchange: 9 ms out, 1 ms back
    t1 = wclock.now()
    base.advance(0.009)
    t2 = base.now()
    base.advance(0.001)
    t4 = wclock.now()
    est.add_sample(t1, t2, t4)
    true_offset = 37.5  # broker = worker + 37.5
    assert abs(est.offset - true_offset) <= est.uncertainty_s + 1e-12


def test_offset_estimator_prefers_min_rtt_sample():
    est = ClockOffsetEstimator()
    # congested exchange: huge RTT, poor estimate
    est.add_sample(0.0, 100.0, 2.0)
    congested = est.offset
    assert est.uncertainty_s == pytest.approx(1.0)
    # clean exchange afterwards: tiny RTT wins regardless of order
    est.add_sample(10.0, 110.0005, 10.001)
    assert est.uncertainty_s == pytest.approx(0.0005)
    assert est.offset != congested
    assert est.offset == pytest.approx(100.0, abs=1e-6)
    assert est.n_samples == 2


def test_offset_estimator_drops_negative_rtt():
    est = ClockOffsetEstimator()
    est.add_sample(5.0, 100.0, 4.0)  # local clock stepped backwards
    assert est.offset is None and est.n_samples == 0


def test_broker_stamp_distinguishes_reply_shapes():
    assert _broker_stamp(("ok",)) is None
    assert _broker_stamp(("slots", 0, 5)) is None
    assert _broker_stamp(("work", 1, 0, b"p", 5, "dynamic")) is None
    assert _broker_stamp(("error", "boom")) is None
    assert _broker_stamp(("ok", 12.5)) == 12.5
    assert _broker_stamp(("slots", 0, 5, 3.25)) == 3.25


# ----------------------------------------------------------- recorder
def test_worker_span_recorder_phases_and_drain():
    clock = VirtualClock(50.0)
    rec = WorkerSpanRecorder("w0", clock)
    tok = rec.begin("worker.simulate")
    clock.advance(0.25)
    rec.end(tok, n_eval=7)
    tok = rec.begin("worker.serialize")
    clock.advance(0.01)
    rec.end(tok, nbytes=123)
    rec.offset.add_sample(0.0, 100.0, 0.002)
    payload = rec.trace_payload()
    assert payload["v"] == 1
    assert [s["name"] for s in payload["spans"]] == [
        "worker.simulate", "worker.serialize"]
    sim = payload["spans"][0]
    assert sim["start"] == pytest.approx(50.0)
    assert sim["end"] == pytest.approx(50.25)
    assert sim["attrs"]["n_eval"] == 7
    assert payload["offset"] == pytest.approx(100.0 - 0.001)
    # drained: the next payload ships only NEW spans
    assert rec.trace_payload()["spans"] == []


def test_worker_span_recorder_bounded_pending():
    clock = VirtualClock()
    rec = WorkerSpanRecorder("w0", clock, max_pending=10)
    for _ in range(25):
        tok = rec.begin("worker.simulate")
        clock.advance(0.001)
        rec.end(tok)
    assert len(rec.trace_payload(limit=100)["spans"]) == 10
    assert rec.n_dropped == 15


def test_record_span_lands_on_pseudo_thread_and_exporter():
    class Sink:
        def __init__(self):
            self.spans = []

        def export(self, sp):
            self.spans.append(sp)

    sink = Sink()
    tracer = Tracer(exporter=sink)
    sp = tracer.record_span("worker.simulate", 10.0, 11.5,
                            thread="worker:abc", worker_id="abc")
    assert sp.thread == "worker:abc"
    assert sp.duration == pytest.approx(1.5)
    assert tracer.spans()[-1] is sp
    assert sink.spans == [sp]
    # the null tracer records nothing, cheaply
    null = pt.NullTracer()
    assert null.record_span("x", 0.0, 1.0).duration == 0.0
    assert null.spans() == []


# ------------------------------------------------- broker-side ingestion
def _exchange(broker, base, wclock, rec, msg, latency=0.001):
    """One simulated stamped round trip over skewed virtual clocks."""
    t1 = wclock.now()
    base.advance(latency)
    reply = broker._dispatch(msg + (t1,))
    base.advance(latency)
    rec.observe_exchange(t1, _broker_stamp(reply), wclock.now())
    return reply


def test_skewed_worker_spans_merge_within_uncertainty():
    """Inject a worker clock 1000 s ahead of the broker's; after
    offset calibration from stamped exchanges, merged spans must land on
    the broker timeline within the RTT-derived uncertainty window."""
    base = VirtualClock(10.0)
    broker = EvalBroker("127.0.0.1", 0, clock=base)
    try:
        broker.start_generation(0, b"payload", 4, batch=4)
        gen = broker._gen
        skew = 1000.0
        wclock = _OffsetClock(base, skew)
        rec = WorkerSpanRecorder("skewed", wclock)
        _exchange(broker, base, wclock, rec, ("hello", "skewed"))
        _exchange(broker, base, wclock, rec,
                  ("get_slots", "skewed", gen, 4))
        assert rec.offset.offset == pytest.approx(-skew, abs=1e-9)
        # a simulate span on the worker clock; remember its TRUE broker-
        # clock interval for the merge assertion
        tok = rec.begin("worker.simulate")
        true_start = base.now()
        base.advance(0.5)
        rec.end(tok, n_eval=4)
        true_end = base.now()
        trace = rec.trace_payload()
        reply = broker._dispatch(
            ("results", "skewed", gen,
             [(i, b"p", True) for i in range(4)], trace)
        )
        assert reply[0] == "done"  # 4 acceptances met the target
        spans = broker.drain_worker_spans()
        sim = [s for s in spans if s["name"] == "worker.simulate"]
        assert len(sim) == 1
        unc = trace["offset_unc"]
        assert unc is not None and unc > 0
        assert abs(sim[0]["start"] - true_start) <= unc + 1e-9
        assert abs(sim[0]["end"] - true_end) <= unc + 1e-9
        assert sim[0]["thread"] == "worker:skewed"
        assert sim[0]["attrs"]["clock_offset_unc_s"] == unc
        # per-worker offset surfaced for the bench / dashboard
        offs = broker.worker_offsets()
        assert offs["skewed"]["offset_s"] == pytest.approx(-skew,
                                                           abs=1e-9)
        # drain is a take: second call returns nothing
        assert broker.drain_worker_spans() == []
    finally:
        broker.stop()


def test_pre_tracing_worker_interoperates_with_new_broker():
    """Old-style messages (no trailing elements) get the exact legacy
    reply shapes — no stamps, no trace expectations — and the broker
    keeps full bookkeeping for them (protocol back-compat)."""
    broker = EvalBroker("127.0.0.1", 0)
    try:
        broker.start_generation(0, b"payload", 2, batch=5)
        gen = broker._gen
        reply = broker._dispatch(("hello", "legacy"))
        assert reply == ("work", gen, 0, b"payload", 5, "dynamic")
        reply = broker._dispatch(("get_slots", "legacy", gen, 5))
        assert reply == ("slots", 0, 5)
        reply = broker._dispatch(("heartbeat", "legacy", gen))
        assert reply == ("ok",)
        reply = broker._dispatch(
            ("results", "legacy", gen, [(0, b"p", True)]))
        assert reply == ("ok",)
        # degraded-mode attribution: no spans, no offsets — gracefully
        assert broker.drain_worker_spans() == []
        assert broker.worker_offsets() == {}
        st = broker.status()
        assert st.workers["legacy"]["n_results"] == 1
        assert not st.workers["legacy"].get("trace", False)
        assert broker._dispatch(("bye", "legacy")) == ("ok",)
        assert broker.status().departed["legacy"]["reason"] == "bye"
    finally:
        broker.stop()


def test_run_worker_no_trace_speaks_legacy_protocol():
    """run_worker(trace=False) against the new broker: the run completes
    and the broker ingests zero spans (degraded mode end to end). The
    worker runs in a thread via the _stop_check seam."""
    import cloudpickle

    from pyabc_tpu.core.population import Particle

    broker = EvalBroker("127.0.0.1", 0)
    stop = threading.Event()
    try:
        def simulate_one():
            return Particle(m=0, parameter={"x": 1.0}, weight=1.0,
                            sum_stat={}, distance=0.1, accepted=True)

        broker.start_generation(
            0, cloudpickle.dumps(simulate_one), 6, batch=3)
        th = threading.Thread(
            target=run_worker,
            args=("127.0.0.1", broker.address[1]),
            kwargs=dict(worker_id="legacy-w", trace=False, poll_s=0.05,
                        _stop_check=stop.is_set),
        )
        th.start()
        triples = broker.wait(timeout=30.0)
        assert len(triples) >= 6
        assert broker.drain_worker_spans() == []
        st = broker.status()
        assert st.workers["legacy-w"]["n_results"] >= 6
        assert "clock_offset_s" not in st.workers["legacy-w"]
    finally:
        stop.set()
        th.join(timeout=10)
        broker.stop()


def test_status_surfaces_last_error_and_presumed_dead():
    clock = VirtualClock(0.0)
    broker = EvalBroker("127.0.0.1", 0, clock=clock, liveness_s=5.0)
    try:
        broker.start_generation(0, b"payload", 100, batch=5)
        gen = broker._gen
        broker._dispatch(("hello", "w1", clock.now()))
        trace = {"v": 1, "spans": [], "offset": 0.0, "offset_unc": 1e-4,
                 "rtt": 2e-4, "last_error": "RuntimeError('model blew up')",
                 "n_eval": 10, "n_acc": 0}
        broker._dispatch(("results", "w1", gen, [], trace))
        st = broker.status()
        assert st.workers["w1"]["last_error"] == (
            "RuntimeError('model blew up')")
        assert not st.workers["w1"]["presumed_dead"]
        # the worker goes silent mid-generation: flagged after the
        # liveness window (the wait()-stalls-dark diagnosis)
        clock.advance(6.0)
        st = broker.status()
        assert st.workers["w1"]["presumed_dead"]
        assert st.workers["w1"]["idle_s"] >= 5.0
        # worker_snapshot (the /api/observability section) carries it too
        snap = broker.worker_snapshot()
        assert snap["w1"]["presumed_dead"]
        assert snap["w1"]["last_error"]
        # a graceful bye leaves a tombstone with reason + error
        broker._dispatch(("bye", "w1", "signal",
                          {"v": 1, "spans": [], "offset": 0.0}))
        st = broker.status()
        assert "w1" not in st.workers
        assert st.departed["w1"]["reason"] == "signal"
        assert st.departed["w1"]["last_error"]
    finally:
        broker.stop()


def test_observability_snapshot_includes_registered_broker_workers():
    from pyabc_tpu.observability import observability_snapshot

    broker = EvalBroker("127.0.0.1", 0)
    try:
        broker._dispatch(("hello", "snap-w", 0.0))
        snap = observability_snapshot()
        assert "snap-w" in snap["workers"]
    finally:
        broker.stop()
    # stop() unregisters: a fresh snapshot no longer reports the pool
    assert "snap-w" not in observability_snapshot()["workers"]


# --------------------------------------------------- gap attribution math
def test_elastic_gap_attribution_categories_and_union():
    spans = [
        # two workers computing concurrently: union, not sum
        {"name": "worker.simulate", "thread": "worker:a",
         "start": 0.0, "end": 4.0, "attrs": {}},
        {"name": "worker.simulate", "thread": "worker:b",
         "start": 2.0, "end": 6.0, "attrs": {}},
        {"name": "worker.serialize", "thread": "worker:a",
         "start": 6.0, "end": 6.5, "attrs": {}},
        {"name": "worker.ship", "thread": "worker:a",
         "start": 6.5, "end": 7.0, "attrs": {}},
        {"name": "worker.wait", "thread": "worker:b",
         "start": 6.0, "end": 8.0, "attrs": {}},
        {"name": "broker.poll_latency", "thread": "MainThread",
         "start": 8.0, "end": 8.5, "attrs": {}},
        # uncategorized orchestrator work still counts as attributed
        {"name": "persist", "thread": "MainThread",
         "start": 8.5, "end": 9.0, "attrs": {}},
    ]
    rep = elastic_gap_attribution(spans, 0.0, 10.0)
    assert rep["window_s"] == pytest.approx(10.0)
    cats = rep["categories"]
    assert cats["worker_compute"]["s"] == pytest.approx(6.0)  # union 0-6
    assert cats["serialization"]["s"] == pytest.approx(0.5)
    assert cats["broker_rtt"]["s"] == pytest.approx(0.5)
    assert cats["queue_wait"]["s"] == pytest.approx(2.0)
    assert cats["orchestrator_poll"]["s"] == pytest.approx(0.5)
    assert rep["attributed_s"] == pytest.approx(9.0)
    assert rep["dark_s"] == pytest.approx(1.0)
    assert rep["attributed_frac"] == pytest.approx(0.9)


def test_elastic_gap_attribution_clips_to_window():
    spans = [{"name": "worker.simulate", "thread": "worker:a",
              "start": -5.0, "end": 5.0, "attrs": {}}]
    rep = elastic_gap_attribution(spans, 0.0, 10.0)
    assert rep["categories"]["worker_compute"]["s"] == pytest.approx(5.0)
    assert rep["attributed_frac"] == pytest.approx(0.5)


def test_worker_trace_spans_filter():
    spans = [
        {"name": "worker.simulate", "thread": "worker:a", "start": 0,
         "end": 1},
        {"name": "broker.poll_latency", "thread": "MainThread",
         "start": 1, "end": 2},
        {"name": "persist", "thread": "MainThread", "start": 2, "end": 3},
    ]
    out = worker_trace_spans(spans)
    assert [d["name"] for d in out] == ["worker.simulate",
                                       "broker.poll_latency"]


# ------------------------------------------------------- end-to-end merge
def test_end_to_end_worker_spans_merge_and_decompose():
    """Real worker subprocesses against a traced run: worker phase spans
    arrive on per-worker pseudo-threads of the run tracer (piggybacked
    on result messages — the worker makes no extra request kinds), the
    poll-latency spans anchor on broker finalization, and the elastic
    accountant decomposes the run with every category populated."""
    tracer = Tracer()
    s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                          generation_timeout=240.0)
    port = s.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    try:
        def sim(pars):
            time.sleep(0.002)
            return {"x": pars["theta"] + 0.5 * np.random.normal()}

        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(pt.SimpleModel(sim, name="gauss_host"), prior,
                        pt.PNormDistance(p=2), population_size=60,
                        eps=pt.QuantileEpsilon(initial_epsilon=1.5,
                                               alpha=0.5),
                        sampler=s, seed=4, tracer=tracer)
        abc.new("sqlite://", {"x": 1.0})
        h = abc.run(max_nr_populations=2)
        assert h.n_populations == 2

        spans = [sp.to_dict() for sp in tracer.spans()]
        wthreads = {d["thread"] for d in spans
                    if d["thread"].startswith("worker:")}
        assert len(wthreads) == 2, f"worker pseudo-threads: {wthreads}"
        names = {d["name"] for d in spans}
        for phase in ("worker.connect", "worker.deserialize",
                      "worker.simulate", "worker.serialize",
                      "worker.ship", "worker.slots",
                      "broker.poll_latency"):
            assert phase in names, f"missing {phase} in {sorted(names)}"
        # every merged span carries its offset calibration
        wspans = [d for d in spans if d["thread"].startswith("worker:")]
        assert all("clock_offset_s" in d["attrs"]
                   and d["attrs"]["clock_offset_unc_s"] is not None
                   for d in wspans)
        # same-host monotonic clocks: offsets are sub-second, and the
        # uncertainty (half the best RTT over loopback) is tiny
        offs = s.broker.worker_offsets()
        assert len(offs) == 2
        assert all(abs(v["offset_s"]) < 1.0 for v in offs.values())
        assert all(0 < v["uncertainty_s"] < 0.1 for v in offs.values())
        # the decomposition over the LAST generation's window (the first
        # generation's window includes worker-subprocess startup — heavy
        # imports before run_worker() even starts, dark by definition):
        # compute dominates this 2 ms-model config, every category
        # populated
        gens = sorted((d for d in spans
                       if d["name"] == "broker.generation"),
                      key=lambda d: d["start"])
        rep = elastic_gap_attribution(
            [d for d in spans
             if d["name"] not in ("run", "setup", "generation", "sample",
                                  "broker.generation")],
            gens[-1]["start"], gens[-1]["end"],
        )
        cats = rep["categories"]
        assert cats["worker_compute"]["s"] > 0
        assert cats["serialization"]["s"] > 0
        assert cats["broker_rtt"]["s"] > 0
        assert rep["attributed_frac"] > 0.6
    finally:
        for p in workers:
            p.kill()
        s.stop()


@pytest.mark.slow
def test_bench_elastic_lane_reports_attribution(monkeypatch):
    """The bench's elastic lane end to end (reduced size): warm runs
    report the five decomposition fracs and the >=0.9 attributed-frac
    regression guard against real worker subprocesses."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_elastic_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._emitted = True  # neuter the atexit emit
    from pyabc_tpu.observability import SYSTEM_CLOCK

    bench.CLOCK = SYSTEM_CLOCK
    bench.TRACER = Tracer(clock=SYSTEM_CLOCK)
    monkeypatch.setenv("PYABC_TPU_BENCH_ELASTIC_POP", "60")
    monkeypatch.setenv("PYABC_TPU_BENCH_ELASTIC_GENS", "2")
    out = bench.run_elastic_lane(120.0)
    warm = [r for r in out["per_run"] if r["warm"]]
    assert warm, out
    for r in warm:
        for key in ("worker_compute_frac", "serialization_frac",
                    "broker_rtt_frac", "queue_wait_frac",
                    "orchestrator_poll_frac"):
            assert 0.0 <= r[key] <= 1.0
        assert r["worker_compute_frac"] > 0
    assert out["regression_guard"]["pass_attributed"], out
    assert out["workers"]["merge_uncertainty_max_s"] < 0.1
    assert out["worker_trace_jsonl"]["n_spans"] > 0
    path = out["worker_trace_jsonl"]["path"]
    if path and os.path.exists(path):
        os.remove(path)
