"""Segmented early-reject execution (ISSUE 15).

The soundness contract under test: with ``early_reject`` ON, the fused
kernel runs proposals segment by segment and retires lanes whose
monotone distance lower bound already exceeds the generation epsilon —
and the ACCEPTED POPULATIONS are bit-identical to the classic
full-trajectory run (same keys, same slot order, only provably-rejected
work skipped). Plus: the bound's monotonicity on random data, capability
gating with named reasons, and the packed-fetch accounting metrics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import gillespie as g
from pyabc_tpu.ops.segment import (
    full_sim_from_segments,
    index_map_for,
    uniform_protocol_reason,
)

SEGMENTS = 5
N_LEAPS = 100
N_OBS = 20


def _bd_model():
    return g.make_birth_death_model(n_leaps=N_LEAPS, n_obs=N_OBS,
                                    segments=SEGMENTS)


def _run(early_reject, *, pop=64, gens=4, seed=11, **kwargs):
    obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                 segments=SEGMENTS)
    abc = pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                    pt.PNormDistance(p=2), population_size=pop,
                    eps=pt.MedianEpsilon(), seed=seed,
                    early_reject=early_reject, fused_generations=4,
                    **kwargs)
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=gens)
    return abc, h


# ---------------------------------------------------------------- protocol

def test_segment_chain_matches_full_sim():
    """The synthesized full simulator IS the segment chain: stepping the
    protocol by hand and scattering through the index map reproduces
    spec.flatten(sim(...)) bit-exactly."""
    model = _bd_model()
    spec = model.sumstat_spec()
    seg = model.segmented
    imap = index_map_for(seg, spec)
    assert imap.shape == (SEGMENTS, seg.seg_size)
    # every flat position is emitted exactly once
    assert sorted(imap.reshape(-1).tolist()) == list(range(spec.total_size))

    key = jax.random.key(3)
    theta = jnp.asarray([1.0, -0.5])
    full = np.asarray(spec.flatten(model.sim(key, theta)))
    carry = seg.init(key, theta)
    buf = np.zeros(spec.total_size, np.float32)
    for j in range(seg.n_segments):
        carry, vals = seg.step(carry, jnp.asarray(j, jnp.int32))
        buf[imap[j]] = np.asarray(vals)
    assert np.array_equal(buf, full)


def test_multi_channel_layout_roundtrip():
    model = g.make_stochastic_lv_model(n_leaps=100, n_obs=20, segments=4)
    spec = model.sumstat_spec()
    imap = index_map_for(model.segmented, spec)
    assert sorted(imap.reshape(-1).tolist()) == list(range(spec.total_size))
    sim2 = full_sim_from_segments(model.segmented)
    out1 = model.sim(jax.random.key(0), jnp.asarray([0.2, -1.9, 0.1]))
    out2 = sim2(jax.random.key(0), jnp.asarray([0.2, -1.9, 0.1]))
    for k in out1:
        assert np.array_equal(np.asarray(out1[k]), np.asarray(out2[k]))


# ------------------------------------------------------------------- bound

def test_pnorm_bound_monotone_and_sound():
    rng = np.random.default_rng(0)
    S = 24
    spec_like = None  # the bound closures never read the spec
    for p in (1.0, 2.0, np.inf):
        dist = pt.PNormDistance(p=p)
        w = jnp.asarray(rng.uniform(0.1, 2.0, S), jnp.float32)
        bound = dist.device_bound_fn(spec_like)
        x = jnp.asarray(rng.normal(size=S), jnp.float32)
        x0 = jnp.asarray(rng.normal(size=S), jnp.float32)
        dfn = dist.device_fn(None)
        full = float(dfn(x, x0, w))
        acc = bound["init"]()
        prev_exceeds = False
        for lo in range(0, S, 6):
            idx = jnp.arange(lo, lo + 6)
            acc = bound["step"](acc, x[idx], idx, x0, w)
            # sound: never declares rejection below the true distance
            assert not bool(bound["exceeds"](acc, jnp.asarray(full), w))
            # monotone: once above a small threshold, stays above
            small = jnp.asarray(full * 0.1)
            now = bool(bound["exceeds"](acc, small, w))
            assert now or not prev_exceeds
            prev_exceeds = now
        # after the full prefix the bound detects any threshold < d
        assert bool(bound["exceeds"](acc, jnp.asarray(full * 0.9), w))


def test_aggregated_bound_sound():
    rng = np.random.default_rng(1)
    S = 16
    d = pt.AggregatedDistance(
        [pt.PNormDistance(p=2), pt.PNormDistance(p=np.inf)],
        weights=[0.7, 1.3],
    )
    d.initialize(0, x_0={"y": np.zeros(S)})
    bound = d.device_bound_fn(None)
    assert bound is not None
    params = d.device_params(None)
    dfn = d.device_fn(None)
    x = jnp.asarray(rng.normal(size=S), jnp.float32)
    x0 = jnp.asarray(rng.normal(size=S), jnp.float32)
    full = float(dfn(x, x0, params))
    acc = bound["init"]()
    for lo in range(0, S, 4):
        idx = jnp.arange(lo, lo + 4)
        acc = bound["step"](acc, x[idx], idx, x0, params)
        assert not bool(bound["exceeds"](acc, jnp.asarray(full), params))
    assert bool(bound["exceeds"](acc, jnp.asarray(full * 0.9), params))


# ----------------------------------------------------------- end to end

def test_early_reject_populations_bit_identical():
    """The headline contract: ON vs OFF accepted populations (theta,
    weights, distances, epsilon trail) are BIT-identical — early reject
    skips only provably-rejected work."""
    abc_on, h_on = _run("auto", seed=11)
    abc_off, h_off = _run(False, seed=11)
    assert h_on.max_t == h_off.max_t
    for t in range(h_on.max_t + 1):
        df1, w1 = h_on.get_distribution(m=0, t=t)
        df2, w2 = h_off.get_distribution(m=0, t=t)
        assert np.array_equal(np.asarray(df1), np.asarray(df2))
        assert np.array_equal(w1, w2)
        ext1 = h_on.get_population_extended(t)
        ext2 = h_off.get_population_extended(t)
        assert np.array_equal(np.asarray(ext1["distance"]),
                              np.asarray(ext2["distance"]))
    # work was actually skipped in the late generations
    retired = [
        (h_on.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h_on.max_t + 1)
    ]
    assert sum(retired) > 0
    occ = (h_on.get_telemetry(h_on.max_t) or {}).get("segment_occupancy")
    assert occ is not None and 0.0 < occ <= 1.0


def test_early_reject_metrics_exported():
    from pyabc_tpu.observability import global_metrics
    from pyabc_tpu.observability.metrics import (
        SIM_LANES_RETIRED_TOTAL,
        SIM_SEGMENT_OCCUPANCY_GAUGE,
    )

    before = global_metrics().counter(SIM_LANES_RETIRED_TOTAL).value
    _run("auto", seed=13, gens=3)
    after = global_metrics().counter(SIM_LANES_RETIRED_TOTAL).value
    assert after > before
    occ = global_metrics().gauge(SIM_SEGMENT_OCCUPANCY_GAUGE).value
    assert 0.0 < occ <= 1.0


# ----------------------------------------------------------------- gating

def test_unsegmented_model_gates_off_with_reason():
    from pyabc_tpu.models import lotka_volterra as lv

    abc = pt.ABCSMC(lv.make_lv_model(), lv.default_prior(),
                    pt.PNormDistance(p=2), population_size=32)
    abc.new("sqlite://", lv.observed_data(seed=123))
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "segmented" in reason


def test_early_reject_required_raises_when_incapable():
    from pyabc_tpu.models import lotka_volterra as lv

    abc = pt.ABCSMC(lv.make_lv_model(), lv.default_prior(),
                    pt.PNormDistance(p=2), population_size=32,
                    early_reject=True, fused_generations=4)
    abc.new("sqlite://", lv.observed_data(seed=123))
    with pytest.raises(ValueError, match="early_reject=True unavailable"):
        abc.run(max_nr_populations=2)


def test_adaptive_distance_gates_off():
    obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                 segments=SEGMENTS)
    abc = pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                    pt.AdaptivePNormDistance(p=2), population_size=32,
                    early_reject="auto")
    abc.new("sqlite://", obs)
    reason = abc._early_reject_incapable_reason(
        adaptive=True, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "adaptive" in reason
    # sharded composition is named too
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=False, sumstat_mode=False,
        sharded_n=8)
    assert reason is not None and "sharded" in reason


def test_uniform_protocol_reason_names_mismatch():
    a = g.make_birth_death_model(segments=5)
    b = g.make_birth_death_model(segments=5)
    assert uniform_protocol_reason([a, b]) is None
    c = g.make_birth_death_model(n_leaps=200, n_obs=20, segments=4)
    assert "differ" in uniform_protocol_reason([a, c])
    from pyabc_tpu.models import lotka_volterra as lv

    assert "no segmented" in uniform_protocol_reason(
        [a, lv.make_lv_model()])


def test_early_reject_arg_validated():
    with pytest.raises(ValueError, match="early_reject"):
        pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                  pt.PNormDistance(p=2), early_reject="yes")
