"""Segmented early-reject execution (ISSUE 15).

The soundness contract under test: with ``early_reject`` ON, the fused
kernel runs proposals segment by segment and retires lanes whose
monotone distance lower bound already exceeds the generation epsilon —
and the ACCEPTED POPULATIONS are bit-identical to the classic
full-trajectory run (same keys, same slot order, only provably-rejected
work skipped). Plus: the bound's monotonicity on random data, capability
gating with named reasons, and the packed-fetch accounting metrics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import gillespie as g
from pyabc_tpu.ops.segment import (
    full_sim_from_segments,
    index_map_for,
    uniform_protocol_reason,
)

SEGMENTS = 5
N_LEAPS = 100
N_OBS = 20


def _bd_model():
    return g.make_birth_death_model(n_leaps=N_LEAPS, n_obs=N_OBS,
                                    segments=SEGMENTS)


def _run(early_reject, *, pop=64, gens=4, seed=11, **kwargs):
    obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                 segments=SEGMENTS)
    abc = pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                    pt.PNormDistance(p=2), population_size=pop,
                    eps=pt.MedianEpsilon(), seed=seed,
                    early_reject=early_reject, fused_generations=4,
                    **kwargs)
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=gens)
    return abc, h


# ---------------------------------------------------------------- protocol

def test_segment_chain_matches_full_sim():
    """The synthesized full simulator IS the segment chain: stepping the
    protocol by hand and scattering through the index map reproduces
    spec.flatten(sim(...)) bit-exactly."""
    model = _bd_model()
    spec = model.sumstat_spec()
    seg = model.segmented
    imap = index_map_for(seg, spec)
    assert imap.shape == (SEGMENTS, seg.seg_size)
    # every flat position is emitted exactly once
    assert sorted(imap.reshape(-1).tolist()) == list(range(spec.total_size))

    key = jax.random.key(3)
    theta = jnp.asarray([1.0, -0.5])
    full = np.asarray(spec.flatten(model.sim(key, theta)))
    carry = seg.init(key, theta)
    buf = np.zeros(spec.total_size, np.float32)
    for j in range(seg.n_segments):
        carry, vals = seg.step(carry, jnp.asarray(j, jnp.int32))
        buf[imap[j]] = np.asarray(vals)
    assert np.array_equal(buf, full)


def test_multi_channel_layout_roundtrip():
    model = g.make_stochastic_lv_model(n_leaps=100, n_obs=20, segments=4)
    spec = model.sumstat_spec()
    imap = index_map_for(model.segmented, spec)
    assert sorted(imap.reshape(-1).tolist()) == list(range(spec.total_size))
    sim2 = full_sim_from_segments(model.segmented)
    out1 = model.sim(jax.random.key(0), jnp.asarray([0.2, -1.9, 0.1]))
    out2 = sim2(jax.random.key(0), jnp.asarray([0.2, -1.9, 0.1]))
    for k in out1:
        assert np.array_equal(np.asarray(out1[k]), np.asarray(out2[k]))


# ------------------------------------------------------------------- bound

def test_pnorm_bound_monotone_and_sound():
    rng = np.random.default_rng(0)
    S = 24
    spec_like = None  # the bound closures never read the spec
    for p in (1.0, 2.0, np.inf):
        dist = pt.PNormDistance(p=p)
        w = jnp.asarray(rng.uniform(0.1, 2.0, S), jnp.float32)
        bound = dist.device_bound_fn(spec_like)
        x = jnp.asarray(rng.normal(size=S), jnp.float32)
        x0 = jnp.asarray(rng.normal(size=S), jnp.float32)
        dfn = dist.device_fn(None)
        full = float(dfn(x, x0, w))
        acc = bound["init"]()
        prev_exceeds = False
        for lo in range(0, S, 6):
            idx = jnp.arange(lo, lo + 6)
            acc = bound["step"](acc, x[idx], idx, x0, w)
            # sound: never declares rejection below the true distance
            assert not bool(bound["exceeds"](acc, jnp.asarray(full), w))
            # monotone: once above a small threshold, stays above
            small = jnp.asarray(full * 0.1)
            now = bool(bound["exceeds"](acc, small, w))
            assert now or not prev_exceeds
            prev_exceeds = now
        # after the full prefix the bound detects any threshold < d
        assert bool(bound["exceeds"](acc, jnp.asarray(full * 0.9), w))


def test_aggregated_bound_sound():
    rng = np.random.default_rng(1)
    S = 16
    d = pt.AggregatedDistance(
        [pt.PNormDistance(p=2), pt.PNormDistance(p=np.inf)],
        weights=[0.7, 1.3],
    )
    d.initialize(0, x_0={"y": np.zeros(S)})
    bound = d.device_bound_fn(None)
    assert bound is not None
    params = d.device_params(None)
    dfn = d.device_fn(None)
    x = jnp.asarray(rng.normal(size=S), jnp.float32)
    x0 = jnp.asarray(rng.normal(size=S), jnp.float32)
    full = float(dfn(x, x0, params))
    acc = bound["init"]()
    for lo in range(0, S, 4):
        idx = jnp.arange(lo, lo + 4)
        acc = bound["step"](acc, x[idx], idx, x0, params)
        assert not bool(bound["exceeds"](acc, jnp.asarray(full), params))
    assert bool(bound["exceeds"](acc, jnp.asarray(full * 0.9), params))


# ----------------------------------------------------------- end to end

def test_early_reject_populations_bit_identical():
    """The headline contract: ON vs OFF accepted populations (theta,
    weights, distances, epsilon trail) are BIT-identical — early reject
    skips only provably-rejected work."""
    abc_on, h_on = _run("auto", seed=11)
    abc_off, h_off = _run(False, seed=11)
    assert h_on.max_t == h_off.max_t
    for t in range(h_on.max_t + 1):
        df1, w1 = h_on.get_distribution(m=0, t=t)
        df2, w2 = h_off.get_distribution(m=0, t=t)
        assert np.array_equal(np.asarray(df1), np.asarray(df2))
        assert np.array_equal(w1, w2)
        ext1 = h_on.get_population_extended(t)
        ext2 = h_off.get_population_extended(t)
        assert np.array_equal(np.asarray(ext1["distance"]),
                              np.asarray(ext2["distance"]))
    # work was actually skipped in the late generations
    retired = [
        (h_on.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h_on.max_t + 1)
    ]
    assert sum(retired) > 0
    occ = (h_on.get_telemetry(h_on.max_t) or {}).get("segment_occupancy")
    assert occ is not None and 0.0 < occ <= 1.0


def test_early_reject_metrics_exported():
    from pyabc_tpu.observability import global_metrics
    from pyabc_tpu.observability.metrics import (
        SIM_LANES_RETIRED_TOTAL,
        SIM_SEGMENT_OCCUPANCY_GAUGE,
    )

    before = global_metrics().counter(SIM_LANES_RETIRED_TOTAL).value
    _run("auto", seed=13, gens=3)
    after = global_metrics().counter(SIM_LANES_RETIRED_TOTAL).value
    assert after > before
    occ = global_metrics().gauge(SIM_SEGMENT_OCCUPANCY_GAUGE).value
    assert 0.0 < occ <= 1.0


# ----------------------------------------------------------------- gating

def test_unsegmented_model_gates_off_with_reason():
    from pyabc_tpu.models import lotka_volterra as lv

    abc = pt.ABCSMC(lv.make_lv_model(), lv.default_prior(),
                    pt.PNormDistance(p=2), population_size=32)
    abc.new("sqlite://", lv.observed_data(seed=123))
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "segmented" in reason


def test_early_reject_required_raises_when_incapable():
    from pyabc_tpu.models import lotka_volterra as lv

    abc = pt.ABCSMC(lv.make_lv_model(), lv.default_prior(),
                    pt.PNormDistance(p=2), population_size=32,
                    early_reject=True, fused_generations=4)
    abc.new("sqlite://", lv.observed_data(seed=123))
    with pytest.raises(ValueError, match="early_reject=True unavailable"):
        abc.run(max_nr_populations=2)


def _gate_abc(dist, acceptor=None, eps=None):
    obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                 segments=SEGMENTS)
    abc = pt.ABCSMC(_bd_model(), g.birth_death_prior(), dist,
                    population_size=32, early_reject="auto",
                    **({"acceptor": acceptor} if acceptor else {}),
                    **({"eps": eps} if eps is not None else {}))
    abc.new("sqlite://", obs)
    # the gate runs after distance init in the real loop
    abc.distance_function.initialize(0, None, abc.x_0)
    return abc


def test_adaptive_gate_lifted_for_moment_scales():
    """ISSUE 17: adaptive distances with moment-expressible scale
    functions run segmented (unbiased per-column moments over ALL
    resolved lanes); the default MAD scale stays gated with a reason
    naming the decomposable alternatives."""
    from pyabc_tpu.distance.scale import standard_deviation

    abc = _gate_abc(pt.AdaptivePNormDistance(
        p=2, scale_function=standard_deviation))
    assert abc._early_reject_incapable_reason(
        adaptive=True, stochastic=False, sumstat_mode=False,
        sharded_n=None) is None
    abc = _gate_abc(pt.AdaptivePNormDistance(p=2))  # MAD default
    reason = abc._early_reject_incapable_reason(
        adaptive=True, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "moment" in reason
    assert "standard_deviation" in reason
    # derived record-column transforms read whole rows: still gated
    abc = _gate_abc(pt.AdaptiveAggregatedDistance(
        [pt.PNormDistance(p=2), pt.PNormDistance(p=1)]))
    reason = abc._early_reject_incapable_reason(
        adaptive=True, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "whole rows" in reason


def test_sharded_gate_lifted():
    """ISSUE 17: the segmented engine runs INSIDE the sharded kernel —
    a shard count no longer gates early reject; only the replicated
    GSPMD mesh path (mesh without sharded) remains excluded."""
    abc = _gate_abc(pt.PNormDistance(p=2))
    assert abc._early_reject_incapable_reason(
        adaptive=False, stochastic=False, sumstat_mode=False,
        sharded_n=8) is None


def test_stochastic_gate_lifted_for_bounded_kernels():
    """ISSUE 17: a StochasticAcceptor retires against per-lane
    pre-committed acceptance thresholds when the kernel provides a
    log-density UPPER bound; distances without one (or with a distance
    LOWER bound) stay gated with the direction named."""
    from pyabc_tpu.epsilon.temperature import ExpDecayFixedIterScheme

    abc = _gate_abc(pt.IndependentNormalKernel(var=4.0),
                    acceptor=pt.StochasticAcceptor(),
                    eps=pt.Temperature(
                        schemes=[ExpDecayFixedIterScheme()]))
    assert abc._early_reject_incapable_reason(
        adaptive=False, stochastic=True, sumstat_mode=False,
        sharded_n=None) is None
    # the AcceptanceRateScheme reweights the ring of ALL evaluations —
    # survivor-biased under retirement, so it keeps the classic kernel
    abc = _gate_abc(pt.IndependentNormalKernel(var=4.0),
                    acceptor=pt.StochasticAcceptor(),
                    eps=pt.Temperature())
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=True, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "AcceptanceRateScheme" in reason
    # a distance LOWER bound cannot decide the stochastic test
    abc = _gate_abc(pt.PNormDistance(p=2))
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=True, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "UPPER" in reason
    # and an upper bound decides ONLY the stochastic test
    abc = _gate_abc(pt.IndependentNormalKernel(var=4.0))
    reason = abc._early_reject_incapable_reason(
        adaptive=False, stochastic=False, sumstat_mode=False,
        sharded_n=None)
    assert reason is not None and "upper bound" in reason


def test_uniform_protocol_reason_names_mismatch():
    a = g.make_birth_death_model(segments=5)
    b = g.make_birth_death_model(segments=5)
    assert uniform_protocol_reason([a, b]) is None
    c = g.make_birth_death_model(n_leaps=200, n_obs=20, segments=4)
    assert "differ" in uniform_protocol_reason([a, c])
    from pyabc_tpu.models import lotka_volterra as lv

    assert "no segmented" in uniform_protocol_reason(
        [a, lv.make_lv_model()])


def test_early_reject_arg_validated():
    with pytest.raises(ValueError, match="early_reject"):
        pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                  pt.PNormDistance(p=2), early_reject="yes")


def test_capability_fallback_telemetry():
    """Satellite (ISSUE 17): a requested-but-incapable fast path is a
    MEASURED event — the gate and reason land in the fallback counter
    (global registry: /api/observability), the run's fallback list (the
    dispatch snapshot) and the first generation's History telemetry."""
    from pyabc_tpu.observability import global_metrics
    from pyabc_tpu.observability.metrics import (
        CAPABILITY_FALLBACKS_TOTAL,
        capability_fallback_metric,
    )

    before = global_metrics().counter(CAPABILITY_FALLBACKS_TOTAL).value
    # segmented models + the default MAD scale: early_reject="auto"
    # falls back loudly at the early_reject gate
    obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                 segments=SEGMENTS)
    abc = pt.ABCSMC(_bd_model(), g.birth_death_prior(),
                    pt.AdaptivePNormDistance(p=2), population_size=32,
                    eps=pt.MedianEpsilon(), seed=3, early_reject="auto",
                    fused_generations=2)
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=2)
    assert abc._capability_fallbacks, "fallback not recorded"
    entry = abc._capability_fallbacks[0]
    assert entry["gate"] == "early_reject"
    assert "moment" in entry["reason"]
    after = global_metrics().counter(CAPABILITY_FALLBACKS_TOTAL).value
    assert after > before
    assert global_metrics().counter(
        capability_fallback_metric("early_reject")).value >= 1
    tel = h.get_telemetry(0) or {}
    assert tel.get("capability_fallbacks"), tel
    assert tel["capability_fallbacks"][0]["gate"] == "early_reject"


# ------------------------------------------- composed paths (ISSUE 17)
#
# The two speed tentpoles compose: the segmented retire/refill engine
# runs INSIDE the sharded kernel (shard-local sweeps over each shard's
# lane-key block), adaptive scales refit unbiased from per-column
# moments over ALL resolved lanes, and stochastic acceptors retire
# against per-lane pre-committed acceptance thresholds. The contracts:
# ON==OFF bit-identity wherever the classic run is the reference
# (uniform + stochastic accepts), posterior parity for adaptive (the
# moment refit is a different — unbiased — estimator than the
# survivor-only ring), and mesh==virtual bit-identity for sharding.

@pytest.mark.slow
def test_stochastic_early_reject_bit_identical():
    """A StochasticAcceptor lane retires only when acceptance is
    provably impossible at its pre-committed draw — accepted
    populations, weights and the temperature trail are BIT-identical
    to the classic full-trajectory run."""
    from pyabc_tpu.epsilon.temperature import ExpDecayFixedIterScheme

    def _run_stoch(early, seed=7):
        obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                     segments=SEGMENTS)
        abc = pt.ABCSMC(
            _bd_model(), g.birth_death_prior(),
            pt.IndependentNormalKernel(var=4.0), population_size=64,
            eps=pt.Temperature(schemes=[ExpDecayFixedIterScheme()],
                               initial_temperature=50.0),
            acceptor=pt.StochasticAcceptor(), seed=seed,
            early_reject=early, fused_generations=4)
        abc.new("sqlite://", obs)
        return abc, abc.run(max_nr_populations=4)

    _abc_on, h_on = _run_stoch("auto")
    _abc_off, h_off = _run_stoch(False)
    assert h_on.max_t == h_off.max_t
    eps_on = h_on.get_all_populations().query(
        "t >= 0")["epsilon"].to_numpy()
    eps_off = h_off.get_all_populations().query(
        "t >= 0")["epsilon"].to_numpy()
    assert np.array_equal(eps_on, eps_off)
    for t in range(h_on.max_t + 1):
        df1, w1 = h_on.get_distribution(m=0, t=t)
        df2, w2 = h_off.get_distribution(m=0, t=t)
        assert np.array_equal(np.asarray(df1), np.asarray(df2))
        assert np.array_equal(w1, w2)
    retired = sum(
        (h_on.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h_on.max_t + 1)
    )
    assert retired > 0


def test_adaptive_early_reject_posterior_parity():
    """Adaptive scales under retirement accumulate moments over ALL
    resolved lanes — a different (unbiased) estimator than the classic
    survivor ring, so the contract is posterior parity plus actually-
    retired work, not bit-identity."""
    from pyabc_tpu.distance.scale import standard_deviation

    def _run_ad(early, seed=5):
        obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                     segments=SEGMENTS)
        abc = pt.ABCSMC(
            _bd_model(), g.birth_death_prior(),
            pt.AdaptivePNormDistance(
                p=2, scale_function=standard_deviation),
            population_size=128, eps=pt.MedianEpsilon(), seed=seed,
            early_reject=early, fused_generations=4)
        abc.new("sqlite://", obs)
        return abc, abc.run(max_nr_populations=5)

    def _post(h):
        df, w = h.get_distribution(0, h.max_t)
        th = np.asarray(df)
        return (th * np.asarray(w)[:, None]).sum(axis=0)

    abc_on, h_on = _run_ad("auto")
    _abc_off, h_off = _run_ad(False)
    retired = sum(
        (h_on.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h_on.max_t + 1)
    )
    assert retired > 0
    np.testing.assert_allclose(_post(h_on), _post(h_off), atol=0.15)
    # the adaptive weights refit each generation under retirement
    w = abc_on.distance_function.weights
    assert len(w) >= 3 and not np.allclose(w[1], w[2])


@pytest.mark.mesh
def test_sharded_segment_bit_identical_to_virtual():
    """The composed tentpole contract: sharded×segmented runs are
    bit-identical between the 8-device mesh and the virtual-shard
    reference, AND to the segmented-off sharded run (early reject
    skips only provably-rejected work, shard-locally)."""
    from jax.sharding import Mesh

    def _run_sh(early, mesh=None, sharded=None, seed=11):
        obs = g.observed_birth_death(n_leaps=N_LEAPS, n_obs=N_OBS,
                                     segments=SEGMENTS)
        abc = pt.ABCSMC(
            _bd_model(), g.birth_death_prior(), pt.PNormDistance(p=2),
            population_size=64, eps=pt.MedianEpsilon(), seed=seed,
            early_reject=early, fused_generations=4, mesh=mesh,
            sharded=sharded)
        abc.new("sqlite://", obs)
        return abc, abc.run(max_nr_populations=4)

    def _arrays(h):
        pops = h.get_all_populations().query("t >= 0")
        out = {"eps": pops["epsilon"].to_numpy()}
        for t in pops["t"]:
            df, w = h.get_distribution(0, int(t))
            out[f"th_{t}"] = np.asarray(df)
            out[f"w_{t}"] = np.asarray(w)
        return out

    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip(f"need 8 virtual cpu devices, have {len(devs)}")
    _abc_v, h_v = _run_sh("auto", sharded=8)
    _abc_off, h_off = _run_sh(False, sharded=8)
    mesh = Mesh(np.asarray(devs[:8]), axis_names=("particles",))
    abc_m, h_m = _run_sh("auto", mesh=mesh)
    a, b, c = _arrays(h_v), _arrays(h_off), _arrays(h_m)
    assert set(a) == set(b) == set(c)
    for k in a:
        np.testing.assert_array_equal(
            a[k], b[k], err_msg=f"sharded seg ON vs OFF diverged at {k}")
        np.testing.assert_array_equal(
            a[k], c[k], err_msg=f"mesh vs virtual seg diverged at {k}")
    # per-shard early-reject accounting rode the packed fetch
    tel = None
    for t in range(h_m.max_t + 1):
        cand = h_m.get_telemetry(t) or {}
        if cand.get("retired_per_shard"):
            tel = cand
            break
    assert tel is not None
    assert len(tel["retired_per_shard"]) == 8
    assert sum(tel["retired_per_shard"]) == tel["retired_early"]
    assert len(tel["segment_occupancy_per_shard"]) == 8
    mesh_block = abc_m._engine.snapshot()["mesh"]
    assert mesh_block["retire_imbalance"] >= 1.0
    assert len(mesh_block["retired_per_device"]) == 8
