"""Preemption bit-identity matrix (round 15 property test).

The claim the mesh-aware scheduler stands on: checkpoint-preempting an
n-shard run at ANY chunk boundary and resuming it on ANY divisor-width
sub-mesh (including virtual shards on one device) reproduces the
uninterrupted run BIT-identically — epsilon trail, thetas, weights,
every generation. The matrix crosses seeded-random preemption
boundaries AND interrupt/resume widths {virtual, 1, 2, 4}: each case
stops through the production graceful path (``request_graceful_stop``
at a chunk boundary -> flush + final checkpoint), rebuilds a fresh
ABCSMC at a DIFFERENT width, resumes via ``load()`` + checkpoint
adoption, and must land exactly on the solo reference.

conftest forces 8 virtual CPU devices, so widths 2 and 4 are real
shard_map sub-meshes (the CI ``mesh`` job's rig)."""
import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

import pyabc_tpu as pt
from pyabc_tpu.inference.smc import GracefulShutdown

pytestmark = pytest.mark.mesh

NOISE_SD = 0.5
POP = 64
GENS = 6
G = 2  # fused chunk length -> 3 chunk boundaries to preempt at
N_SHARDS = 4


def _model():
    @pt.JaxModel.from_function(["theta"], name="gauss_preempt")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _mesh(width):
    """None = no mesh (virtual shards). Width 1 is still a REAL mesh:
    shard_map over one device with all 4 shards vmapped inside it — a
    distinct execution path from the no-mesh vmap."""
    if width is None:
        return None
    devs = jax.devices("cpu")
    if len(devs) < width:
        pytest.skip(f"need {width} virtual cpu devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:width]), axis_names=("particles",))


def _make(db, *, width, seed=21, checkpoint_path=None):
    abc = pt.ABCSMC(
        _model(), pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.PNormDistance(p=2), population_size=POP,
        eps=pt.MedianEpsilon(), seed=seed, mesh=_mesh(width),
        sharded=N_SHARDS, fused_generations=G,
        checkpoint_path=checkpoint_path,
    )
    return abc


def _history_arrays(h):
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    out = [eps]
    for t in range(h.n_populations):
        df, w = h.get_distribution(0, t)
        out.append(np.sort(df["theta"].to_numpy()))
        out.append(np.sort(np.asarray(w)))
    return out


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The uninterrupted solo run (virtual shards — the canonical
    n-shard reduction)."""
    db = f"sqlite:///{tmp_path_factory.mktemp('ref')}/ref.db"
    abc = _make(db, width=None)
    abc.new(db, {"x": 1.0})
    h = abc.run(max_nr_populations=GENS)
    assert h.n_populations == GENS
    return _history_arrays(h)


@pytest.mark.parametrize("resume_width", [None, 1, 2, 4],
                         ids=["virtual", "w1", "w2", "w4"])
def test_preempt_any_boundary_resume_any_width_bit_identical(
        reference, resume_width, tmp_path):
    """One matrix row: interrupt at a seeded-random chunk boundary on a
    seeded-random width, resume at ``resume_width`` — full-History
    bit-identity vs the uninterrupted reference."""
    rng = random.Random(1000 + (resume_width or 0))
    boundary = rng.choice([1, 2])  # chunks completed before the stop
    interrupt_width = rng.choice(
        [w for w in (None, 1, 2, 4) if w != resume_width])

    db = f"sqlite:///{tmp_path}/run.db"
    ck = str(tmp_path / "run.ck")
    abc = _make(db, width=interrupt_width, checkpoint_path=ck)
    abc.new(db, {"x": 1.0})
    abc_id = int(abc.history.id)
    chunks = {"n": 0}

    def on_chunk(ev):
        chunks["n"] += 1
        if chunks["n"] >= boundary:
            # the scheduler's preemption path: graceful stop at the
            # chunk boundary -> flush + final checkpoint
            abc.request_graceful_stop()

    abc.chunk_event_cb = on_chunk
    with pytest.raises(GracefulShutdown):
        abc.run(max_nr_populations=GENS)
    interrupted_at = abc.history.n_populations
    assert 0 < interrupted_at < GENS, (
        f"boundary {boundary} did not interrupt mid-run "
        f"(persisted {interrupted_at}/{GENS})")

    # resume on a DIFFERENT width: fresh ABCSMC, same statistical
    # config, checkpoint adoption inside run()
    abc2 = _make(db, width=resume_width, checkpoint_path=ck)
    abc2.load(db, abc_id)
    h = abc2.run(max_nr_populations=GENS)
    assert h.n_populations == GENS
    got = _history_arrays(h)
    assert len(got) == len(reference)
    for a, b in zip(reference, got):
        assert np.array_equal(a, b), (
            f"resume width {resume_width} after boundary {boundary} on "
            f"width {interrupt_width} diverged from the uninterrupted "
            f"run")


# --------------------------------------------- adaptive carries (round 16)
#
# ISSUE 12 satellite: checkpoint-preemption bit-identity asserted for
# ADAPTIVE chunk carries — the adaptive-distance scale state rides the
# carry's dist_w slot and the stochastic acceptor's temperature trail +
# pdf-norm recursion ride the eps/acc_state slots; a preempted run must
# resume them mid-trail bit-identically on a different width.

def _make_flavored(flavor, db, *, width, seed=31, checkpoint_path=None):
    from pyabc_tpu.distance.scale import standard_deviation

    if flavor == "adaptive":
        dist = pt.AdaptivePNormDistance(
            p=2, scale_function=standard_deviation)
        eps = pt.MedianEpsilon()
        acceptor = None
        model = _model()
    else:  # stochastic
        from pyabc_tpu.epsilon.temperature import ExpDecayFixedIterScheme

        dist = pt.IndependentNormalKernel(var=[NOISE_SD**2])
        # exp-decay ladder from a pinned high start: the trail spans the
        # full run, so the preemption genuinely interrupts a live
        # temperature recursion (the default acceptance-rate initial
        # would land at T=1 immediately for this well-matched model)
        eps = pt.Temperature(schemes=[ExpDecayFixedIterScheme()],
                             initial_temperature=100.0)
        acceptor = pt.StochasticAcceptor()

        @pt.JaxModel.from_function(["theta"], name="det_preempt")
        def model(key, theta):
            return {"x": theta[0]}

    return pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        dist, population_size=pt.ListPopulationSize(
            [POP, POP - 12, POP, POP - 24, POP, POP]),
        eps=eps, acceptor=acceptor, seed=seed, mesh=_mesh(width),
        sharded=N_SHARDS, fused_generations=G,
        checkpoint_path=checkpoint_path,
    )


# ----------------------------------------- segmented cells (ISSUE 17)
#
# The composed tentpole: the segmented early-reject engine runs INSIDE
# the sharded kernel, and the preemption matrix extends to it — a
# sharded segmented run preempted at one width resumes at another
# bit-identically, with early reject ON the whole way.

def _make_segmented(db, *, width, seed=41, checkpoint_path=None):
    from pyabc_tpu.models import gillespie as g

    return pt.ABCSMC(
        g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5),
        g.birth_death_prior(), pt.PNormDistance(p=2),
        population_size=POP, eps=pt.MedianEpsilon(), seed=seed,
        early_reject="auto", mesh=_mesh(width), sharded=N_SHARDS,
        fused_generations=G, checkpoint_path=checkpoint_path,
    )


def _seg_history_arrays(h):
    """_history_arrays for the 2-parameter birth-death model: rows
    lex-sorted (slot order differs across widths), weights reordered
    alongside."""
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    out = [eps]
    for t in range(h.n_populations):
        df, w = h.get_distribution(0, t)
        th = df.to_numpy()
        order = np.lexsort(th.T)
        out.append(th[order])
        out.append(np.asarray(w)[order])
    return out


@pytest.mark.slow
def test_preempt_segmented_sharded_bit_identical(tmp_path):
    """Mesh × segmented cell: interrupt the width-2 sharded
    early-reject run at the first chunk boundary, resume at width 4 —
    full-History bit-identity vs the uninterrupted virtual-shard run,
    with lanes actually retired along the way."""
    from pyabc_tpu.models import gillespie as g

    obs = g.observed_birth_death(n_leaps=100, n_obs=20, segments=5)
    ref_db = f"sqlite:///{tmp_path}/ref_seg.db"
    ref = _make_segmented(ref_db, width=None)
    ref.new(ref_db, obs)
    h_ref = ref.run(max_nr_populations=GENS)
    reference = _seg_history_arrays(h_ref)
    assert h_ref.n_populations == GENS

    db = f"sqlite:///{tmp_path}/run_seg.db"
    ck = str(tmp_path / "run_seg.ck")
    abc = _make_segmented(db, width=2, checkpoint_path=ck)
    abc.new(db, obs)
    abc_id = int(abc.history.id)

    def on_chunk(ev):
        abc.request_graceful_stop()

    abc.chunk_event_cb = on_chunk
    with pytest.raises(GracefulShutdown):
        abc.run(max_nr_populations=GENS)
    assert 0 < abc.history.n_populations < GENS

    abc2 = _make_segmented(db, width=4, checkpoint_path=ck)
    abc2.load(db, abc_id)
    h = abc2.run(max_nr_populations=GENS)
    assert h.n_populations == GENS
    got = _seg_history_arrays(h)
    assert len(got) == len(reference)
    for a, b in zip(reference, got):
        assert np.array_equal(a, b), (
            "segmented sharded preempt/resume diverged from the "
            "uninterrupted run")
    retired = sum(
        (h.get_telemetry(t) or {}).get("retired_early", 0)
        for t in range(h.n_populations)
    )
    assert retired > 0


@pytest.mark.parametrize("flavor", ["adaptive", "stochastic"])
def test_preempt_adaptive_carry_bit_identical(flavor, tmp_path):
    """One adaptive cell per flavor: interrupt the width-2 run at the
    first chunk boundary, resume at width 4 — scale state / temperature
    trail / pdf-norm carry restored bit-identically vs the solo
    virtual-shard run."""
    ref_db = f"sqlite:///{tmp_path}/ref_{flavor}.db"
    ref = _make_flavored(flavor, ref_db, width=None)
    ref.new(ref_db, {"x": 1.0})
    h_ref = ref.run(max_nr_populations=GENS)
    reference = _history_arrays(h_ref)
    assert h_ref.n_populations == GENS

    db = f"sqlite:///{tmp_path}/run_{flavor}.db"
    ck = str(tmp_path / f"run_{flavor}.ck")
    abc = _make_flavored(flavor, db, width=2, checkpoint_path=ck)
    abc.new(db, {"x": 1.0})
    abc_id = int(abc.history.id)

    def on_chunk(ev):
        abc.request_graceful_stop()

    abc.chunk_event_cb = on_chunk
    with pytest.raises(GracefulShutdown):
        abc.run(max_nr_populations=GENS)
    assert 0 < abc.history.n_populations < GENS

    abc2 = _make_flavored(flavor, db, width=4, checkpoint_path=ck)
    abc2.load(db, abc_id)
    h = abc2.run(max_nr_populations=GENS)
    assert h.n_populations == GENS
    got = _history_arrays(h)
    assert len(got) == len(reference)
    for a, b in zip(reference, got):
        assert np.array_equal(a, b), (
            f"{flavor} preempt/resume diverged from the uninterrupted "
            f"run")


# ------------------------------------- learned-sumstat cell (ISSUE 20)
#
# The fitted Fearnhead-Prangle transform rides the chunk carry
# (dist_w["ss"]) and the checkpoint (format v3); a preempted run must
# resume the predictor params mid-run on a different width and land
# bit-identically — mirror_fitted_params stores the fetched float32
# values verbatim, so the resume-rebuilt carry equals the carried
# device operands bitwise.

def _make_learned(db, *, width, seed=61, checkpoint_path=None):
    @pt.JaxModel.from_function(["theta"], name="fp_preempt")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        sig = theta[0] + NOISE_SD * jax.random.normal(k1, (2,))
        noise = 5.0 * jax.random.normal(k2, (4,))
        return {"sig": sig, "noise": noise}

    return pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
            pt.LinearPredictor())),
        population_size=POP, eps=pt.MedianEpsilon(), seed=seed,
        mesh=_mesh(width), sharded=N_SHARDS, fused_generations=G,
        checkpoint_path=checkpoint_path,
    )


def test_preempt_learned_sumstat_w2_resume_w4_bit_identical(tmp_path):
    """Learned-transform cell: interrupt the width-2 sharded run with
    in-kernel boundary fits at the first post-seed chunk boundary,
    resume at width 4 — full-History bit-identity vs the uninterrupted
    virtual-shard run, with the device-fit plan active on BOTH legs."""
    obs = {"sig": np.asarray([1.0, 1.0]), "noise": np.zeros(4)}
    ref_db = f"sqlite:///{tmp_path}/ref_ss.db"
    ref = _make_learned(ref_db, width=None)
    ref.new(ref_db, obs)
    h_ref = ref.run(max_nr_populations=GENS)
    assert h_ref.n_populations == GENS
    assert ref._sumstat_device_plan is not None
    reference = _history_arrays(h_ref)

    db = f"sqlite:///{tmp_path}/run_ss.db"
    ck = str(tmp_path / "run_ss.ck")
    abc = _make_learned(db, width=2, checkpoint_path=ck)
    abc.new(db, obs)
    abc_id = int(abc.history.id)
    events = {"n": 0}

    def on_chunk(ev):
        # event 1 is the generation-0 HOST seed-fit collect; stop at
        # the first REAL chunk boundary so fitted params are mid-carry
        events["n"] += 1
        if events["n"] >= 2:
            abc.request_graceful_stop()

    abc.chunk_event_cb = on_chunk
    with pytest.raises(GracefulShutdown):
        abc.run(max_nr_populations=GENS)
    assert 0 < abc.history.n_populations < GENS

    abc2 = _make_learned(db, width=4, checkpoint_path=ck)
    abc2.load(db, abc_id)
    h = abc2.run(max_nr_populations=GENS)
    assert h.n_populations == GENS
    assert abc2._sumstat_device_plan is not None
    got = _history_arrays(h)
    assert len(got) == len(reference)
    for a, b in zip(reference, got):
        assert np.array_equal(a, b), (
            "learned-sumstat preempt/resume diverged from the "
            "uninterrupted run")
