"""The observability subsystem: spans, metrics, exporters, coverage.

Unit-level: span nesting and thread-safety, JSONL round trip into the
coverage accountant, the null-tracer overhead guard, registry/Prometheus
exports, and the window-throughput math the bench now delegates here.
Integration-level: a real fused SMC run on CPU writes a parseable JSONL
trace with nested calibration -> generation/chunk -> fetch/process spans
and a persist/db.write trail, and the coverage accountant attributes a
positive fraction of its wall clock.
"""
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.observability import (
    MetricsRegistry,
    NULL_TRACER,
    NullTracer,
    Tracer,
    VirtualClock,
    coverage_report,
    interval_union,
    JsonlTraceExporter,
    prometheus_text,
    read_trace,
    window_throughput,
)


# ---------------------------------------------------------------- tracer

def test_span_nesting_and_attributes():
    vc = VirtualClock()
    tr = Tracer(clock=vc)
    with tr.span("run") as root:
        vc.advance(1.0)
        with tr.span("generation", t=0) as gen:
            vc.advance(2.0)
            gen.set(n_accepted=100)
        vc.advance(0.5)
    spans = {s.name: s for s in tr.spans()}
    assert spans["generation"].parent_id == spans["run"].span_id
    assert spans["run"].parent_id is None
    assert spans["generation"].attrs == {"t": 0, "n_accepted": 100}
    assert spans["generation"].duration == pytest.approx(2.0)
    assert spans["run"].duration == pytest.approx(3.5)
    assert root.end is not None


def test_span_error_is_recorded_and_stack_unwound():
    tr = Tracer(clock=VirtualClock())
    with pytest.raises(ValueError):
        with tr.span("outer"):
            with tr.span("inner"):
                raise ValueError("boom")
    spans = {s.name: s for s in tr.spans()}
    assert "boom" in spans["inner"].attrs["error"]
    assert "boom" in spans["outer"].attrs["error"]
    assert tr.current_span() is None  # stack fully unwound


def test_tracer_thread_safety_under_thread_pool():
    tr = Tracer()  # real clock: exercises the actual lock paths
    n_threads, n_spans = 8, 50

    def work(i):
        for k in range(n_spans):
            with tr.span("outer", worker=i):
                with tr.span("inner", k=k):
                    pass

    with ThreadPoolExecutor(max_workers=n_threads) as ex:
        list(ex.map(work, range(n_threads)))
    spans = tr.spans()
    assert len(spans) == n_threads * n_spans * 2
    by_id = {s.span_id: s for s in spans}
    inners = [s for s in spans if s.name == "inner"]
    assert len(inners) == n_threads * n_spans
    for s in inners:
        parent = by_id[s.parent_id]
        # parent linkage never crosses threads
        assert parent.name == "outer" and parent.thread == s.thread


def test_tracer_bounded_memory():
    tr = Tracer(clock=VirtualClock(), max_spans=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.spans()) == 10
    assert tr.n_dropped == 15
    assert tr.snapshot()["n_dropped"] == 15


def test_null_tracer_is_inert_and_cheap():
    nt = NullTracer()
    with nt.span("anything", t=1) as sp:
        sp.set(foo=2)
    assert nt.spans() == [] and nt.snapshot()["n_spans"] == 0
    # overhead guard: the disabled path must stay no-op-cheap enough to
    # live on per-chunk/per-generation hot paths unconditionally
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        with nt.span("hot", t=3):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 10e-6, f"null span costs {per_call * 1e6:.2f}us"


# --------------------------------------------------------------- metrics

def test_metrics_registry_counters_gauges_histograms():
    vc = VirtualClock()
    reg = MetricsRegistry(clock=vc)
    reg.counter("acc").inc(3)
    reg.counter("acc").inc(2)  # get-or-create returns the same instrument
    reg.gauge("depth").set(7)
    reg.gauge("depth").dec(2)
    h = reg.histogram("lat")
    with h.time():
        vc.advance(0.25)
    h.observe(0.75)
    snap = reg.snapshot()
    assert snap["acc"] == 5.0
    assert snap["depth"] == 5.0
    assert snap["lat"]["count"] == 2
    assert snap["lat"]["sum"] == pytest.approx(1.0)
    assert snap["lat"]["max"] == 0.75
    with pytest.raises(TypeError):
        reg.gauge("acc")  # type clash must not silently alias


def test_prometheus_text_round_trip():
    reg = MetricsRegistry(clock=VirtualClock())
    reg.counter("particles", "accepted particles").inc(42)
    reg.gauge("backlog").set(3)
    reg.histogram("fetch_s").observe(0.01)
    text = prometheus_text(reg)
    assert "# TYPE particles_total counter" in text
    assert "particles_total 42" in text
    assert "backlog 3" in text
    assert 'fetch_s_bucket{le="+Inf"} 1' in text
    assert "fetch_s_count 1" in text


# -------------------------------------------------- coverage accountant

def test_interval_union_merges_overlaps():
    assert interval_union([(0, 1), (0.5, 2), (3, 4)]) == pytest.approx(3.0)
    assert interval_union([]) == 0.0


def test_coverage_report_per_thread_and_overall():
    spans = [
        {"name": "a", "thread": "T1", "start": 0.0, "end": 4.0},
        {"name": "b", "thread": "T1", "start": 1.0, "end": 2.0},  # nested
        {"name": "c", "thread": "T2", "start": 6.0, "end": 8.0},
    ]
    rep = coverage_report(spans, t0=0.0, t1=10.0)
    assert rep["window_s"] == 10.0
    assert rep["attributed_s"] == pytest.approx(6.0)  # [0,4] + [6,8]
    assert rep["attributed_frac"] == pytest.approx(0.6)
    assert rep["dark_s"] == pytest.approx(4.0)
    assert rep["per_thread"]["T1"]["attributed_frac"] == pytest.approx(0.4)
    assert rep["per_thread"]["T2"]["attributed_frac"] == pytest.approx(0.2)
    # clipping: a span half outside the window counts half
    rep2 = coverage_report(spans, t0=2.0, t1=6.0)
    assert rep2["attributed_s"] == pytest.approx(2.0)
    # exclude_names: a blanket root span must not hide the gaps
    spans_with_root = spans + [
        {"name": "run", "thread": "T1", "start": 0.0, "end": 10.0}
    ]
    assert coverage_report(spans_with_root, 0.0, 10.0)[
        "attributed_frac"] == pytest.approx(1.0)
    assert coverage_report(spans_with_root, 0.0, 10.0,
                           exclude_names=("run",))[
        "attributed_frac"] == pytest.approx(0.6)


def test_jsonl_export_round_trip_into_coverage(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    vc = VirtualClock()
    tr = Tracer(clock=vc, exporter=JsonlTraceExporter(path))
    with tr.span("run", db="x"):
        vc.advance(2.0)
        with tr.span("generation", t=0):
            vc.advance(3.0)
    parsed = read_trace(path)
    assert [p["name"] for p in parsed] == ["generation", "run"]  # end order
    assert parsed[0]["attrs"] == {"t": 0}
    assert parsed[0]["parent_id"] == parsed[1]["span_id"]
    rep = coverage_report(parsed)
    assert rep["attributed_frac"] == pytest.approx(1.0)
    assert rep["window_s"] == pytest.approx(5.0)


def test_read_trace_tolerates_truncated_tail(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"name": "ok", "thread": "T",
                             "start": 0.0, "end": 1.0}) + "\n")
        fh.write('{"name": "crash-mid-wri')  # no newline, cut off
    assert [s["name"] for s in read_trace(path)] == ["ok"]


def test_window_throughput_matches_bench_semantics():
    # 4 windows of 1s over [0, 4); two off-boundary events per window
    events = [(0.25 + 0.5 * k, 10) for k in range(8)]
    wt = window_throughput(events, 0.0, 4.0, 1.0)
    assert wt["n_windows"] == 4
    assert wt["per_window"] == [20.0, 20.0, 20.0, 20.0]
    assert wt["aggregate_per_s"] == pytest.approx(20.0)
    # boundary semantics (identical to the round-5 bench): an event ON a
    # window edge belongs to the NEXT window, except the span's end edge
    # clamps into the last window; an event AT t0 is excluded
    wtb = window_throughput([(0.0, 1), (1.0, 1), (4.0, 1)], 0.0, 4.0, 1.0)
    assert wtb["per_window"] == [0.0, 1.0, 0.0, 1.0]
    # events outside the span are excluded; span truncates to whole windows
    wt2 = window_throughput([(0.1, 5), (3.9, 5), (10.0, 99)], 0.0, 3.5, 1.0)
    assert wt2["n_windows"] == 3
    assert wt2["n_items"] == 5  # only the 0.1s event lands in [0, 3]


# ------------------------------------------------------------ integration

NOISE_SD = 0.5
X_OBS = 1.0


def _gauss_model():
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def test_fused_run_writes_nested_jsonl_trace(tmp_path):
    """A small fused run on CPU must produce a parseable JSONL trace
    whose spans cover calibration -> chunk -> fetch/process and a
    db.write trail on the writer thread, and the coverage accountant
    must attribute >0 generations' worth of wall clock."""
    path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(exporter=JsonlTraceExporter(path))
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                    population_size=64, eps=pt.MedianEpsilon(),
                    seed=7, fused_generations=4, tracer=tracer)
    assert abc._fused_chunk_capable()
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=4)
    assert h.n_populations == 4

    parsed = read_trace(path)
    names = {p["name"] for p in parsed}
    assert {"run", "calibration", "chunk", "fetch", "process",
            "dispatch", "db.write"} <= names
    by_id = {p["span_id"]: p for p in parsed}
    chunks = [p for p in parsed if p["name"] == "chunk"]
    # nested links: fetch/process children point at their chunk
    for p in parsed:
        if p["name"] in ("fetch", "process"):
            assert by_id[p["parent_id"]]["name"] == "chunk"
    # chunk attrs carry the pipeline accounting
    assert sum(c["attrs"]["g_done"] for c in chunks) >= 4
    assert all("n_acc" in c["attrs"] and "chunk_s" in c["attrs"]
               for c in chunks)
    # the async writer's spans live on ITS thread, one per generation
    writes = [p for p in parsed if p["name"] == "db.write"]
    assert len(writes) >= 4
    assert {p["thread"] for p in writes}.isdisjoint(
        {c["thread"] for c in chunks}
    ) or len({p["thread"] for p in parsed}) == 1
    # coverage accountant: attributed fraction over the run window is
    # meaningfully positive, and >0 generations are attributed
    run_span = next(p for p in parsed if p["name"] == "run")
    rep = coverage_report(parsed, run_span["start"], run_span["end"])
    assert rep["attributed_frac"] > 0.5
    assert rep["n_spans"] >= len(parsed) - 1
    assert rep["per_thread"]  # at least the orchestrator thread appears


def test_serial_run_generation_spans_and_null_default():
    """The host (serial) loop nests sample/persist/adapt under each
    generation span; with no tracer configured nothing is recorded and
    the run still works (null-path guard)."""
    tracer = Tracer()
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))

    def sim(pars):
        return {"x": pars["theta"] + NOISE_SD * np.random.normal()}

    abc = pt.ABCSMC(pt.SimpleModel(sim, name="g"), prior,
                    pt.PNormDistance(p=2), population_size=50,
                    eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
                    sampler=pt.SingleCoreSampler(), seed=3, tracer=tracer)
    abc.new("sqlite://", {"x": X_OBS})
    abc.run(max_nr_populations=2)
    spans = tracer.spans()
    gens = [s for s in spans if s.name == "generation"]
    assert [s.attrs["t"] for s in gens] == [0, 1]
    assert all(s.attrs["n_accepted"] == 50 for s in gens)
    by_id = {s.span_id: s for s in spans}
    for name in ("sample", "persist", "adapt"):
        children = [s for s in spans if s.name == name]
        assert len(children) == 2
        assert all(by_id[c.parent_id].name == "generation"
                   for c in children)

    # default path: no tracer passed and no env var -> NULL_TRACER
    abc2 = pt.ABCSMC(pt.SimpleModel(sim, name="g"), prior,
                     pt.PNormDistance(p=2), population_size=20,
                     eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
                     sampler=pt.SingleCoreSampler(), seed=3)
    assert abc2.tracer is NULL_TRACER or not abc2.tracer.enabled
    abc2.new("sqlite://", {"x": X_OBS})
    abc2.run(max_nr_populations=1)  # runs clean with tracing disabled


def test_env_var_enables_default_tracer(tmp_path, monkeypatch):
    path = str(tmp_path / "env_trace.jsonl")
    monkeypatch.setenv("PYABC_TPU_TRACE", path)
    from pyabc_tpu.observability import default_tracer

    tr = default_tracer()
    assert tr.enabled
    assert default_tracer() is tr  # shared process-wide
    with tr.span("probe"):
        pass
    assert any(s["name"] == "probe" for s in read_trace(path))


def test_visserver_observability_endpoint():
    from urllib.request import urlopen

    from pyabc_tpu.observability import global_metrics, set_global_tracer
    from pyabc_tpu.visserver.server import serve

    tracer = Tracer()
    set_global_tracer(tracer)
    try:
        with tracer.span("probe_span"):
            pass
        global_metrics().counter("probe_counter").inc(2)
        httpd = serve("sqlite://", port=0, block=False)
        try:
            port = httpd.server_port
            with urlopen(f"http://127.0.0.1:{port}/api/observability",
                         timeout=10) as resp:
                payload = json.loads(resp.read())
            assert payload["tracer"]["spans_by_name"]["probe_span"][
                "count"] == 1
            assert payload["metrics"]["probe_counter"] == 2.0
            # round 8: the elastic-pool section is always present
            # (empty unless a broker is live in-process)
            assert isinstance(payload["workers"], dict)
        finally:
            httpd.shutdown()
            httpd.server_close()
    finally:
        set_global_tracer(None)


def test_history_writer_backlog_gauge():
    """The async writer exposes its backlog through the registry and
    attributes its work with db.write spans."""
    from pyabc_tpu.storage.history import History

    reg = MetricsRegistry()
    tr = Tracer()
    h = History("sqlite://")
    h.tracer, h.metrics = tr, reg
    h.store_initial_data(None, {}, {"x": np.asarray([1.0])}, {}, ["m0"],
                         "{}", "{}", "{}")
    h.start_async_writer()
    barrier = threading.Event()
    h._writer.submit(barrier.wait)  # block the writer thread
    h._writer.submit(lambda: None)
    assert reg.snapshot()["pyabc_tpu_db_writer_backlog"] >= 1
    barrier.set()
    h.flush()
    assert reg.snapshot()["pyabc_tpu_db_writer_backlog"] == 0
    assert any(s.name == "db.write" for s in tr.spans())
    h.close()


# ------------------------------------------------------------ sync ledger

def test_sync_ledger_counts_kinds_bytes_and_floor():
    """SyncLedger (round 6): device round trips recorded per kind with
    payload bytes; the floor model turns the count into attributed wall
    clock for the bench's gap_attribution block."""
    from pyabc_tpu.observability import NULL_SYNC_LEDGER, SyncLedger

    vc = VirtualClock()
    led = SyncLedger(clock=vc)
    assert led.count == 0 and led.summary()["tunnel_floor_s"] == 0.0
    led.record("chunk_fetch", 96_000)
    vc.advance(0.5)
    led.record("chunk_fetch", 96_000)
    led.record("compute_probe")
    assert led.count == 3
    assert led.by_kind() == {"chunk_fetch": 2, "compute_probe": 1}
    assert led.total_bytes() == 192_000
    s = led.summary(sync_floor_s=0.1)
    assert s["syncs"] == 3
    assert s["tunnel_floor_s"] == pytest.approx(0.3)
    assert s["bytes_by_kind"]["chunk_fetch"] == 192_000
    # events carry the injected clock's timestamps
    assert led.events[0][0] == 0.0 and led.events[1][0] == 0.5
    led.clear()
    assert led.count == 0
    # the shared inert ledger records nothing
    NULL_SYNC_LEDGER.record("chunk_fetch", 1)
    assert NULL_SYNC_LEDGER.count == 0
    assert NULL_SYNC_LEDGER.summary()["syncs"] == 0


def test_sync_ledger_thread_safety():
    from pyabc_tpu.observability import SyncLedger

    led = SyncLedger()
    with ThreadPoolExecutor(max_workers=8) as pool:
        for f in [pool.submit(lambda: [led.record("k", 8)
                                       for _ in range(100)])
                  for _ in range(8)]:
            f.result()
    assert led.count == 800
    assert led.total_bytes() == 6400


def test_fused_run_records_chunk_fetch_syncs():
    """A fused CPU run books one chunk_fetch sync per fetched chunk,
    with the measured post-compaction payload bytes attached."""
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                    population_size=100, eps=pt.MedianEpsilon(), seed=3,
                    fused_generations=3)
    abc.new("sqlite://", {"x": 1.0}, store_sum_stats=False)
    abc.run(max_nr_populations=6)
    kinds = abc.sync_ledger.by_kind()
    assert kinds.get("chunk_fetch", 0) >= 2  # 6 gens / G=3 chunks
    fetch_events = [e for e in abc.sync_ledger.events
                    if e[1] == "chunk_fetch"]
    assert all(b > 0 for _ts, _k, b in fetch_events)
    # the summary feeds the bench's run_infos["syncs"] block verbatim
    s = abc.sync_ledger.summary(0.102)
    assert s["syncs"] == abc.sync_ledger.count
    assert s["tunnel_floor_s"] == pytest.approx(s["syncs"] * 0.102)


def test_interval_intersection():
    from pyabc_tpu.observability import interval_intersection

    a = [(0.0, 2.0), (3.0, 5.0)]
    b = [(1.0, 4.0)]
    assert interval_intersection(a, b) == pytest.approx(2.0)
    assert interval_intersection(a, []) == 0.0
    assert interval_intersection([(0, 1)], [(2, 3)]) == 0.0
    # identical sets intersect to their union length
    assert interval_intersection(a, a) == pytest.approx(4.0)


def test_device_busy_spans_from_probe_events():
    """The device-busy pseudo-thread (ROADMAP device-busy correlation):
    consecutive compute-probe completions become device.busy spans —
    chunk k's compute runs from max(done_{k-1}, dispatch_k) to done_k —
    and feed the SAME coverage accountant on a synthetic thread."""
    from pyabc_tpu.observability import coverage_report, device_busy_spans

    # (dispatch_ts, done_ts): chunk 1 dispatched at 0 done at 2; chunk 2
    # dispatched at 0.5 (while 1 runs) done at 3.5; chunk 3 dispatched
    # at 5 (idle gap) done at 6
    probes = [(0.0, 2.0), (0.5, 3.5), (5.0, 6.0)]
    spans = device_busy_spans(probes)
    ivs = [(s["start"], s["end"]) for s in spans]
    assert ivs == [(0.0, 2.0), (2.0, 3.5), (5.0, 6.0)]
    assert all(s["thread"] == "device" and s["name"] == "device.busy"
               for s in spans)
    rep = coverage_report(spans, 0.0, 6.0)
    per = rep["per_thread"]["device"]
    # busy 0..3.5 and 5..6 of a 6s window
    assert per["attributed_frac"] == pytest.approx(4.5 / 6.0)


def test_device_busy_separates_fetch_wait_from_tunnel():
    """Inside a chunk-fetch wait, the accountant can now separate
    "device still computing" from "host waiting on the tunnel" — the
    fetch span intersected with the device.busy pseudo-spans."""
    from pyabc_tpu.observability import (
        device_busy_spans,
        interval_intersection,
    )

    # device busy 0..3; the host's fetch span waits 2..5 — 1s of that
    # wait overlaps device compute, 2s is exposed tunnel wait
    busy = device_busy_spans([(0.0, 3.0)])
    fetch_ivs = [(2.0, 5.0)]
    busy_ivs = [(s["start"], s["end"]) for s in busy]
    overlap = interval_intersection(fetch_ivs, busy_ivs)
    assert overlap == pytest.approx(1.0)
    assert (5.0 - 2.0) - overlap == pytest.approx(2.0)
