"""All-samplers statistical equivalence suite.

The reference's signature pattern (SURVEY.md §4 "Distributed: samplers"):
ONE statistical integration test parametrized over ALL samplers — every
execution strategy must produce the same posterior within tolerance
(reference test/base/test_samplers.py). Multi-process samplers run real
forks on this host, exactly as the reference tests real local
infrastructure.
"""
import concurrent.futures as cf

import numpy as np
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)

POP = 100
EPS_LIST = [1.0, 0.6, 0.4]


def _host_model(pars):
    return {"x": pars["theta"] + NOISE_SD * np.random.normal()}


def _sampler_factories():
    return {
        "singlecore": lambda: pt.SingleCoreSampler(),
        "multicore_eval": lambda: pt.MulticoreEvalParallelSampler(n_procs=2),
        "multicore_particle": lambda: pt.MulticoreParticleParallelSampler(
            n_procs=2
        ),
        "mapping": lambda: pt.MappingSampler(map_=map, chunk_size=8),
        "concurrent_future": lambda: pt.ConcurrentFutureSampler(
            cf.ThreadPoolExecutor(max_workers=4), batch_size=8
        ),
    }


@pytest.mark.parametrize("name", [
    # the multi-process samplers fork real worker pools — integration
    # weight that belongs to the full lane, not the tier-1 fast lane
    pytest.param(n, marks=pytest.mark.slow)
    if n.startswith("multicore") else n
    for n in sorted(_sampler_factories())
])
def test_sampler_posterior_equivalence(name):
    """Same Gaussian-conjugate posterior from every host execution strategy."""
    sampler = _sampler_factories()[name]()
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    np.random.seed(17)
    abc = pt.ABCSMC(
        pt.SimpleModel(_host_model), prior, pt.PNormDistance(p=2),
        population_size=POP, eps=pt.ListEpsilon(EPS_LIST), sampler=sampler,
    )
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=len(EPS_LIST))
    assert h.n_populations == len(EPS_LIST)
    df, w = h.get_distribution(0)
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
    assert mu == pytest.approx(POST_MU, abs=0.3)
    assert sd == pytest.approx(np.sqrt(POST_VAR), abs=0.25)
    assert sampler.nr_evaluations_ >= POP


def test_batched_device_sampler_equivalence():
    """The TPU-native batched sampler lands on the same posterior."""
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                    population_size=300, eps=pt.ListEpsilon(EPS_LIST), seed=11)
    assert isinstance(abc.sampler, pt.BatchedSampler)
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=len(EPS_LIST))
    df, w = h.get_distribution(0)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(POST_MU, abs=0.25)


@pytest.mark.slow
def test_multicore_eval_adaptive_distance_records():
    """record_rejected plumbing through forked workers: the adaptive distance
    must receive all-simulation records and refit per-statistic weights."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    np.random.seed(3)
    dist = pt.AdaptivePNormDistance(p=2)
    abc = pt.ABCSMC(
        pt.SimpleModel(_host_model), prior, dist,
        population_size=60, eps=pt.QuantileEpsilon(
            initial_epsilon=1.0, alpha=0.5),
        sampler=pt.MulticoreEvalParallelSampler(n_procs=2),
    )
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=2)
    assert h.n_populations == 2
    # adaptive weights were fitted beyond the initial calibration
    assert any(t >= 1 for t in dist.weights)


@pytest.mark.slow
def test_multicore_worker_exception_propagates():
    """get_if_worker_healthy re-raises child failures instead of hanging."""

    def exploding(pars):
        raise ValueError("boom in worker")

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(
        pt.SimpleModel(exploding), prior, pt.PNormDistance(p=2),
        population_size=20, eps=pt.ListEpsilon([1.0]),
        sampler=pt.MulticoreEvalParallelSampler(n_procs=2),
    )
    abc.new("sqlite://", {"x": X_OBS})
    with pytest.raises(RuntimeError, match="worker(s)? died"):
        abc.run(max_nr_populations=1)
