"""SGE adapter tests.

Multi-node-as-local: a stub ``qsub`` parses the generated array-job script
and runs every task as a local subprocess; a stub ``qstat`` reports an
empty queue. This exercises the REAL file contract (pickled function/args,
task entry point, result collection) without a cluster — the reference's
pattern of testing distributed paths against real local infrastructure
(SURVEY.md §4).
"""
import os
import pickle
import stat
import subprocess
import sys
import textwrap

import pytest

from pyabc_tpu.sge import (
    SGE,
    DefaultContext,
    NamedPrinter,
    ProfilingContext,
    nr_cores_available,
    sge_available,
)

QSUB_STUB = textwrap.dedent("""\
    #!{python}
    import re, subprocess, sys
    script = open(sys.argv[-1]).read()
    n = int(re.search(r"#\\$ -t 1-(\\d+)", script).group(1))
    cmd_line = [l for l in script.splitlines()
                if "pyabc_tpu.sge.job" in l][0]
    for task in range(1, n + 1):
        cmd = cmd_line.replace("$SGE_TASK_ID", str(task)).split()
        subprocess.run(cmd, check=True)
    print("12345.1-%d:1" % n)
""")

QSTAT_STUB = "#!{python}\nprint('')\n"


@pytest.fixture
def fake_sge(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    for name, content in (("qsub", QSUB_STUB), ("qstat", QSTAT_STUB)):
        p = bindir / name
        p.write_text(content.format(python=sys.executable))
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return bindir


# the mapped function must be importable from the job subprocess (same
# constraint as the reference's pickled jobs) — use a stdlib callable
import operator

_NEG = operator.neg


def test_sge_unavailable_raises(monkeypatch, tmp_path):
    monkeypatch.setenv("PATH", str(tmp_path))
    assert not sge_available()
    with pytest.raises(RuntimeError, match="qsub"):
        SGE()


@pytest.mark.slow
def test_sge_map(fake_sge):
    assert sge_available()
    sge = SGE(chunk_size=2, poll_interval_s=0.05)
    out = sge.map(_NEG, list(range(7)))
    assert out == [-x for x in range(7)]


def test_sge_map_profiling_context(fake_sge, tmp_path):
    sge = SGE(execution_context=DefaultContext, poll_interval_s=0.05)
    out = sge.map(_NEG, [3, 4])
    assert out == [-3, -4]


def test_named_printer(capsys):
    with NamedPrinter("worker-1"):
        print("hello")
    assert "[worker-1] hello" in capsys.readouterr().out


def test_nr_cores_available():
    assert nr_cores_available() >= 1


def test_default_context():
    with DefaultContext():
        pass
