"""Numerical & statistical health guards (round 10): the fused loop
detects silent degradation IN-KERNEL and recovers or fails loudly.

The acceptance criteria end-to-end, all deterministic on CPU and `not
slow`: a fused run with an injected mid-chunk ``nan_poison`` carry
corruption completes with posterior parity vs the seed-matched
fault-free run (rollback to the last healthy carry + redispatch — the
recovered trajectory is BIT-identical, the strongest form of parity,
with exactly one rolled-back chunk); a run with an unrecoverable
injected degeneracy terminates with a typed ``DegenerateRunError``
carrying the per-generation health trail; and health detection adds
ZERO blocking syncs (``SyncLedger`` counts identical with the guards on
and off). Plus unit coverage of the health-word bits, the stall
recursion, the Cholesky jitter-escalation ladder, the corruption fault
kinds, and the graceful SIGTERM path (external kill == injected kill).
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.observability import MetricsRegistry, Tracer
from pyabc_tpu.resilience import (
    DegenerateRunError,
    FaultPlan,
    FaultRule,
    decode_health,
    install_fault_plan,
    maybe_corrupt,
    maybe_fault,
    uninstall_fault_plan,
)
from pyabc_tpu.resilience.health import RunSupervisor

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

NOISE_SD = 0.5
X_OBS = 1.0


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    uninstall_fault_plan()
    yield
    uninstall_fault_plan()


# ------------------------------------------------------- health-word units
def test_health_word_bits_and_decode():
    import jax.numpy as jnp

    from pyabc_tpu.ops import health as H

    n = 8
    res = {"theta": jnp.ones((n, 2))}
    k_mask = jnp.arange(n) < 4
    w = jnp.full((n,), 0.25)
    d = jnp.linspace(0.1, 0.4, n)
    word, ess = H.population_bits(
        res, k_mask, w, d, jnp.asarray(4), ess_floor=0.0,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(0.5),
        acc_floor=0.0,
    )
    assert int(word) == H.HEALTH_OK
    assert float(ess) == pytest.approx(4.0)

    # NaN theta in an accepted row
    res_bad = {"theta": jnp.asarray(res["theta"]).at[1, 0].set(jnp.nan)}
    word, _ = H.population_bits(
        res_bad, k_mask, w, d, jnp.asarray(4), ess_floor=0.0,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(0.5), acc_floor=0.0,
    )
    assert int(word) & H.BIT_NAN_THETA
    assert "nan_theta" in decode_health(int(word))
    # ...but a NaN in a MASKED row is not evidence
    res_pad = {"theta": jnp.asarray(res["theta"]).at[6, 0].set(jnp.nan)}
    word, _ = H.population_bits(
        res_pad, k_mask, w, d, jnp.asarray(4), ess_floor=0.0,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(0.5), acc_floor=0.0,
    )
    assert int(word) == H.HEALTH_OK

    # zero total weight with accepted rows; ESS floor; acceptance floor
    w0 = jnp.zeros((n,))
    word, _ = H.population_bits(
        res, k_mask, w0, d, jnp.asarray(4), ess_floor=0.0,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(0.5), acc_floor=0.0,
    )
    assert int(word) & H.BIT_WEIGHT_ZERO
    w_skew = jnp.where(jnp.arange(n) == 0, 1.0, 0.0)
    word, ess = H.population_bits(
        res, k_mask, w_skew, d, jnp.asarray(4), ess_floor=0.5,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(0.5), acc_floor=0.0,
    )
    assert float(ess) == pytest.approx(1.0)
    assert int(word) & H.BIT_ESS_FLOOR
    word, _ = H.population_bits(
        res, k_mask, w, d, jnp.asarray(4), ess_floor=0.0,
        n_target=jnp.asarray(4), acc_rate=jnp.asarray(1e-6),
        acc_floor=1e-3,
    )
    assert int(word) & H.BIT_ACC_COLLAPSE

    assert decode_health(0) == []
    assert set(decode_health(H.BIT_PSD_FAIL | H.BIT_EPS_STALL)) == {
        "psd_fail", "eps_stall"}


def test_eps_stall_recursion():
    import jax.numpy as jnp

    from pyabc_tpu.ops import health as H

    # window 2, rtol 1e-3: two consecutive sub-rtol improvements fire
    eps_prev = jnp.asarray(jnp.inf)
    bit, cnt = H.eps_stall_update(eps_prev, jnp.asarray(1.0),
                                  jnp.asarray(0, jnp.int32),
                                  window=2, rtol=1e-3)
    # inf seed counts as full improvement: no stall
    assert int(bit) == 0 and int(cnt) == 0
    bit, cnt = H.eps_stall_update(jnp.asarray(1.0), jnp.asarray(0.9999),
                                  cnt, window=2, rtol=1e-3)
    assert int(bit) == 0 and int(cnt) == 1
    bit, cnt = H.eps_stall_update(jnp.asarray(0.9999),
                                  jnp.asarray(0.99985), cnt,
                                  window=2, rtol=1e-3)
    assert int(bit) == H.BIT_EPS_STALL and int(cnt) == 2
    # a real improvement resets the counter
    bit, cnt = H.eps_stall_update(jnp.asarray(0.99985), jnp.asarray(0.5),
                                  cnt, window=2, rtol=1e-3)
    assert int(bit) == 0 and int(cnt) == 0
    # window 0 = disabled (fixed schedules)
    bit, cnt = H.eps_stall_update(jnp.asarray(1.0), jnp.asarray(1.0),
                                  jnp.asarray(5, jnp.int32),
                                  window=0, rtol=1e-3)
    assert int(bit) == 0


def test_params_unhealthy_and_poison_kinds():
    import jax.numpy as jnp

    from pyabc_tpu.ops import health as H

    params = {"thetas": jnp.ones((4, 2)),
              "weights": jnp.full((4,), 0.25),
              "chol": jnp.eye(2)}
    fitted = jnp.asarray([True])
    assert not bool(H.params_unhealthy((params,), fitted))

    carry = ((params,), jnp.zeros(()), fitted)
    for kind, leaf in [("nan_poison", "thetas"), ("cov_corrupt", "chol"),
                      ("weight_zero", "weights")]:
        poisoned = H.poison_carry(carry, kind)
        assert bool(H.params_unhealthy(poisoned[0], fitted)), kind
        # the CLEAN carry is untouched (rollback depends on it)
        assert bool(jnp.all(jnp.isfinite(carry[0][0][leaf])))
        assert not bool(H.params_unhealthy(carry[0], fitted))
    # an UNFITTED model's placeholder params are not evidence
    assert not bool(H.params_unhealthy(
        (H.poison_carry(carry, "nan_poison")[0][0],),
        jnp.asarray([False])))
    with pytest.raises(ValueError):
        H.poison_carry(carry, "bogus")


def test_chol_jitter_escalation():
    import jax.numpy as jnp

    from pyabc_tpu.transition.util import (
        device_chol_guarded,
        device_chol_guarded_batched,
    )

    # a healthy SPD matrix: factor finite, no failure, cov unchanged
    cov = jnp.asarray([[2.0, 0.5], [0.5, 1.0]])
    chol, cov_used, bad = device_chol_guarded(cov)
    assert not bool(bad)
    assert np.allclose(np.asarray(chol @ chol.T), np.asarray(cov),
                       atol=1e-6)
    # an indefinite matrix: the ladder must rescue it (the old single
    # 1e-10 retry could not — the needed jitter exceeds 1e-10 * trace)
    cov_bad = jnp.asarray([[1.0, 1.0000505], [1.0000505, 1.0]])
    chol, cov_used, bad = device_chol_guarded(cov_bad)
    assert not bool(bad)
    assert bool(jnp.all(jnp.isfinite(chol)))
    # NaN input cannot be rescued — surfaced, not swallowed
    _, _, bad = device_chol_guarded(jnp.full((2, 2), jnp.nan))
    assert bool(bad)

    covs = jnp.stack([cov, cov_bad, jnp.eye(2)])
    chols, _covs, bad = device_chol_guarded_batched(covs)
    assert not bool(bad)
    assert bool(jnp.all(jnp.isfinite(chols)))


def test_supervisor_action_mapping_and_budget():
    from pyabc_tpu.ops import health as H

    assert RunSupervisor.action_for(H.BIT_NAN_WEIGHT) == "rollback"
    assert RunSupervisor.action_for(H.BIT_WEIGHT_ZERO
                                    | H.BIT_PSD_FAIL) == "rollback"
    assert RunSupervisor.action_for(H.BIT_PSD_FAIL) == "refit"
    assert RunSupervisor.action_for(H.BIT_ESS_FLOOR) == "widen"
    assert RunSupervisor.action_for(H.BIT_ACC_COLLAPSE) == "widen"
    assert RunSupervisor.action_for(H.BIT_EPS_STALL
                                    | H.BIT_NAN_THETA) == "terminate"

    sup = RunSupervisor(max_rollbacks=2)
    assert sup.on_failure(3, H.BIT_NAN_THETA, ess=1.0) == "rollback"
    assert sup.on_failure(3, H.BIT_NAN_THETA) == "rollback"
    with pytest.raises(DegenerateRunError) as ei:
        sup.on_failure(3, H.BIT_NAN_THETA)
    assert len(ei.value.trail) == 3
    assert ei.value.trail[0]["kinds"] == ["nan_theta"]
    # a stall is terminal regardless of remaining budget
    sup2 = RunSupervisor(max_rollbacks=5)
    with pytest.raises(DegenerateRunError):
        sup2.on_failure(1, H.BIT_EPS_STALL)


def test_corruption_kinds_are_polled_not_probed():
    plan = FaultPlan.parse("device.carry:nan_poison:after=1")
    install_fault_plan(plan)
    # probe() ignores corruption rules entirely (no raise, no counting)
    maybe_fault("device.carry")
    maybe_fault("device.carry")
    assert plan.n_fired() == 0
    # poll(): after=1 skips the first poll, fires the second, one-shot
    assert maybe_corrupt("device.carry") is None
    assert maybe_corrupt("device.carry") == "nan_poison"
    assert maybe_corrupt("device.carry") is None
    assert plan.n_fired("device.carry") == 1
    # without a plan, polling is a no-op
    uninstall_fault_plan()
    assert maybe_corrupt("device.carry") is None


# ----------------------------------------------------- fused end-to-end
def _gauss_jax_model():
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _fused_abc(seed=7, pop=100, G=4, **kwargs):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                    population_size=pop, eps=pt.MedianEpsilon(),
                    seed=seed, fused_generations=G, **kwargs)
    abc.new("sqlite://", {"x": X_OBS})
    return abc


def test_nan_poison_recovers_to_bit_identical_posterior():
    """Acceptance criterion #1: an injected mid-chunk ``nan_poison``
    carry corruption is detected by the in-kernel health word, the chunk
    is aborted and rolled back to the last healthy carry, and the run
    completes with POSTERIOR PARITY vs the seed-matched fault-free run —
    bit-identical here, because the rollback target IS the state the
    fault-free run chained from — with exactly one rolled-back chunk."""
    gens = 8
    abc_ref = _fused_abc()
    h_ref = abc_ref.run(max_nr_populations=gens)
    assert h_ref.n_populations == gens
    assert abc_ref.health_supervisor.trail == []  # healthy run: silent

    reg = MetricsRegistry()
    tracer = Tracer()
    abc = _fused_abc(tracer=tracer, metrics=reg)
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="nan_poison", after=1,
                  max_fires=1),
    ]))
    try:
        h = abc.run(max_nr_populations=gens)
    finally:
        uninstall_fault_plan()
    assert h.n_populations == gens

    # exactly one rolled-back chunk, with the diagnosis on the trail
    sup = abc.health_supervisor
    assert sup.rollbacks == 1
    assert len(sup.trail) == 1
    ev = sup.trail[0]
    assert ev["action"] == "rollback"
    assert set(ev["kinds"]) & {"nan_theta", "nan_weight", "weight_zero",
                               "psd_fail"}
    assert ev["recovery_source"] in ("last_good_carry", "checkpoint")

    # bit-identical trajectory vs the fault-free run
    eps_ref = h_ref.get_all_populations().query("t >= 0")["epsilon"]
    eps_fix = h.get_all_populations().query("t >= 0")["epsilon"]
    assert np.array_equal(eps_ref.to_numpy(), eps_fix.to_numpy())
    for t in range(gens):
        df_r, w_r = h_ref.get_distribution(0, t)
        df_f, w_f = h.get_distribution(0, t)
        assert np.array_equal(np.sort(df_r["theta"].to_numpy()),
                              np.sort(df_f["theta"].to_numpy())), t
        assert np.array_equal(np.sort(w_r), np.sort(w_f)), t
    # every generation persisted exactly once (the aborted chunk's
    # degraded generations never reached the History)
    ts = h.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(ts) == sorted(set(ts)) == list(range(gens))

    # observability: counters + a recovery span on the health thread
    snap = reg.snapshot()
    assert snap.get("pyabc_tpu_health_events_total", 0) == 1
    assert snap.get("pyabc_tpu_health_chunk_rollbacks_total", 0) == 1
    assert any(k.startswith("pyabc_tpu_health_events_total_")
               for k in snap)
    spans = [s.to_dict() for s in tracer.spans()]
    rb = [s for s in spans if s["name"] == "health.rollback"]
    assert len(rb) == 1 and rb[0]["thread"] == "health"


def test_nan_poison_recovers_lv_fused():
    """The acceptance criterion's exact workload: a fused LOTKA-VOLTERRA
    run (the bench headline config, shrunk to CPU scale) with an
    injected mid-chunk nan_poison completes with posterior parity vs the
    seed-matched fault-free run — bit-identical via the rollback path,
    with exactly one rolled-back chunk."""
    from pyabc_tpu.models import lotka_volterra as lv

    gens = 8

    def make():
        abc = pt.ABCSMC(
            lv.make_lv_model(), lv.default_prior(),
            pt.AdaptivePNormDistance(p=2), population_size=60,
            eps=pt.MedianEpsilon(), seed=17, fused_generations=4,
        )
        abc.new("sqlite://", lv.observed_data(seed=123),
                store_sum_stats=False)
        return abc

    ref = make()
    h_ref = ref.run(max_nr_populations=gens)
    assert h_ref.n_populations == gens
    assert ref.health_supervisor.trail == []

    abc = make()
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="nan_poison", after=1,
                  max_fires=1),
    ]))
    try:
        h = abc.run(max_nr_populations=gens)
    finally:
        uninstall_fault_plan()
    assert h.n_populations == gens
    assert abc.health_supervisor.rollbacks == 1
    eps_ref = h_ref.get_all_populations().query("t >= 0")["epsilon"]
    eps_fix = h.get_all_populations().query("t >= 0")["epsilon"]
    assert np.array_equal(eps_ref.to_numpy(), eps_fix.to_numpy())
    for t in (0, gens - 1):
        df_r, w_r = h_ref.get_distribution(0, t)
        df_f, w_f = h.get_distribution(0, t)
        for col in df_r.columns:
            assert np.array_equal(np.sort(df_r[col].to_numpy()),
                                  np.sort(df_f[col].to_numpy())), (t, col)
        assert np.array_equal(np.sort(w_r), np.sort(w_f)), t


def test_unrecoverable_poison_terminates_with_trail():
    """Acceptance criterion #2: a degeneracy that survives every
    recovery attempt (the carry is re-poisoned on every dispatch)
    terminates the run with a typed DegenerateRunError carrying the
    per-generation health trail — and the History keeps every healthy
    generation persisted before the failure."""
    abc = _fused_abc(max_health_rollbacks=2)
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="nan_poison", after=1,
                  every=1, max_fires=None),
    ]))
    try:
        with pytest.raises(DegenerateRunError) as ei:
            abc.run(max_nr_populations=8)
    finally:
        uninstall_fault_plan()
    trail = ei.value.trail
    assert len(trail) == 3  # 2 budgeted recoveries + the terminal event
    assert all(e["t"] == trail[0]["t"] for e in trail)
    assert trail[-1]["action"] == "terminate"
    # the healthy generations before the failure are flushed + readable
    pops = abc.history.get_all_populations().query("t >= 0")
    assert len(pops) == trail[0]["t"]


def test_cov_corrupt_detected_and_recovered():
    """A corrupted covariance (non-finite Cholesky factors, the PSD
    failure shape) is detected via the psd_fail bit and the run
    completes after one recovery. The injected corruption cascades into
    a non-finite epsilon as well (no lane can accept), so the stronger
    rollback action outranks the pure-PSD forced refit — the
    psd_fail-only -> refit mapping is covered at the unit level in
    test_supervisor_action_mapping_and_budget."""
    abc = _fused_abc()
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="cov_corrupt", after=1,
                  max_fires=1),
    ]))
    try:
        h = abc.run(max_nr_populations=8)
    finally:
        uninstall_fault_plan()
    assert h.n_populations == 8
    sup = abc.health_supervisor
    assert len(sup.trail) == 1
    assert "psd_fail" in sup.trail[0]["kinds"]
    assert sup.trail[0]["action"] in ("refit", "rollback")
    assert "recovery_source" in sup.trail[0]


def test_ess_floor_triggers_widening_then_terminates():
    """An impossible ESS floor exercises the proposal-widening action
    (bandwidth inflation on the host rebuild, counted in metrics), and —
    since widening cannot fix an impossible floor — the budgeted
    recovery ends in a typed DegenerateRunError whose trail carries the
    ess_floor diagnosis."""
    # no fault plan: this is a REAL statistical floor violation,
    # detected without any injection
    reg = MetricsRegistry()
    abc = _fused_abc(ess_floor=0.99, max_health_rollbacks=2, metrics=reg)
    with pytest.raises(DegenerateRunError) as ei:
        abc.run(max_nr_populations=8)
    trail = ei.value.trail
    assert any("ess_floor" in e["kinds"] for e in trail)
    assert any(e["action"] == "widen" for e in trail)
    assert reg.snapshot().get(
        "pyabc_tpu_health_proposal_widenings_total", 0) >= 1


def test_eps_stall_terminates_gracefully():
    """An epsilon-progress stall (here: an absurd rtol that declares any
    improvement a stall) terminates the run with DegenerateRunError
    instead of burning device time forever."""
    abc = _fused_abc(eps_stall_window=3, eps_stall_rtol=10.0)
    with pytest.raises(DegenerateRunError) as ei:
        abc.run(max_nr_populations=8)
    assert any("eps_stall" in e["kinds"] for e in ei.value.trail)
    assert ei.value.trail[-1]["action"] == "terminate"


def test_health_detection_adds_zero_blocking_syncs():
    """Acceptance criterion #3: the health word rides the existing
    packed fetch — SyncLedger-verified sync counts are IDENTICAL with
    the guards on and off, and so is the sampled trajectory."""
    abc_on = _fused_abc(health_checks=True)
    h_on = abc_on.run(max_nr_populations=8)
    abc_off = _fused_abc(health_checks=False)
    h_off = abc_off.run(max_nr_populations=8)
    s_on = abc_on.sync_ledger.summary(0.1)
    s_off = abc_off.sync_ledger.summary(0.1)
    assert s_on["syncs"] == s_off["syncs"]
    assert s_on["by_kind"] == s_off["by_kind"]
    eps_on = h_on.get_all_populations().query("t >= 0")["epsilon"]
    eps_off = h_off.get_all_populations().query("t >= 0")["epsilon"]
    assert np.array_equal(eps_on.to_numpy(), eps_off.to_numpy())


# ------------------------------------------------- graceful SIGTERM/SIGINT
_SIGTERM_CHILD = """
import sys
import jax
import pyabc_tpu as pt
from pyabc_tpu.epsilon import ConstantEpsilon
from pyabc_tpu.inference.smc import GracefulShutdown

@pt.JaxModel.from_function(["theta"], name="gauss")
def model(key, theta):
    return {"x": theta[0] + 0.5 * jax.random.normal(key)}

db, ck = sys.argv[1], sys.argv[2]
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=100,
                eps=ConstantEpsilon(2.0), seed=5, fused_generations=4,
                checkpoint_path=ck, checkpoint_every=1)
abc.new(db, {"x": 1.0})
try:
    abc.run(max_nr_populations=100000)
    print("DONE", flush=True)
except GracefulShutdown:
    print("GRACEFUL", flush=True)
"""


def test_sigterm_flushes_and_checkpoints(tmp_path):
    """Satellite: an EXTERNAL SIGTERM mid-run is as recoverable as an
    injected orchestrator kill — the handler converts it to
    GracefulShutdown, the fused loop flushes the async History writer
    and writes a final checkpoint, and a fresh orchestrator resumes
    mid-chunk from it."""
    db = f"sqlite:///{tmp_path}/run.db"
    ck = str(tmp_path / "carry.ck")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGTERM_CHILD, db, ck], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 180.0
        while not os.path.exists(ck):
            assert proc.poll() is None, proc.communicate()[1][-2000:]
            assert time.monotonic() < deadline, "no checkpoint appeared"
            time.sleep(0.1)
        # at least one chunk is durable: deliver the external kill
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        proc.kill()
    assert "GRACEFUL" in out, (out, err[-2000:])
    assert proc.returncode == 0
    assert os.path.exists(ck), "final checkpoint missing after SIGTERM"

    from pyabc_tpu.epsilon import ConstantEpsilon
    from pyabc_tpu.resilience import CheckpointManager

    t_ck = int(CheckpointManager(ck).load()["t"])
    assert t_ck >= 4  # at least one full chunk
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc2 = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                     population_size=100, eps=ConstantEpsilon(2.0),
                     seed=5, fused_generations=4, checkpoint_path=ck,
                     checkpoint_every=1)
    abc2.load(db, 1)
    h2 = abc2.run(max_nr_populations=t_ck + 4)
    assert abc2.resumed_from_checkpoint_t == t_ck
    assert h2.n_populations == t_ck + 4
