"""Multi-tenant serving layer (round 14): chaos-tested containment.

The contract under test, end to end on CPU:

1. CHAOS ISOLATION — with a FaultPlan killing / NaN-poisoning ONE
   tenant (scoped by ``fault_scope``), every surviving tenant completes
   with a posterior BIT-IDENTICAL to its seed-matched solo run: a
   neighbor's death is invisible except through OS scheduling.
2. RUN LEASES — a tenant whose orchestrator thread dies hard mid-chunk
   (injected kill: no report, no goodbye) is discovered, its device
   slot reclaimed, and the tenant requeued to resume from its PR-5
   checkpoint — the final trajectory bit-identical to an uninterrupted
   run.
3. ADMISSION — a full queue answers with typed backpressure
   (AdmissionRejectedError + measured retry-after), never unbounded
   queueing.
4. DRAIN — SIGTERM semantics: every live tenant flushes its History
   and writes a final checkpoint before the scheduler reports drained.
5. NAMESPACING — two interleaved runs keep separate tracer/metrics
   namespaces in ``observability_snapshot()`` (the pre-round-14
   one-run-per-process collision), snapshots race-free while both run.
6. ZERO COMPILE — a repeat-shape tenant adopts the shape-keyed kernel
   cache and records NO compile-marked dispatch span.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.observability import observability_snapshot
from pyabc_tpu.resilience import (
    FaultPlan,
    FaultRule,
    install_fault_plan,
    uninstall_fault_plan,
)
from pyabc_tpu.resilience.faults import (
    InjectedPersistError,
    current_fault_scope,
    fault_scope,
)
from pyabc_tpu.serving import (
    CANCELLED,
    COMPLETED,
    DRAINED,
    FAILED,
    RUNNING,
    AdmissionRejectedError,
    RunScheduler,
    TenantSpec,
    serve_api,
)
from pyabc_tpu.storage import History, WriterPool

# the cheap fused gaussian config every serving test rides (one compiled
# shape for the whole module thanks to the shared XLA disk cache +
# in-process kernel cache)
POP = 100
GENS = 6
G = 2


def spec_for(seed: int, gens: int = GENS, pop: int = POP,
             **kw) -> TenantSpec:
    return TenantSpec(model="gaussian", population_size=pop,
                      generations=gens, seed=seed, fused_generations=G,
                      **kw)


def solo_reference(seed: int, db: str, gens: int = GENS,
                   pop: int = POP, sharded: int | None = None) -> History:
    """A seed-matched SOLO run of the tenant gaussian config — the
    parity baseline chaos survivors are compared against (same model
    builder, no scheduler in the loop). ``sharded=n`` runs the n-shard
    reduction VIRTUALLY on one device — by the kernel's width-
    independence contract that is the bit-level reference for a
    scheduler-placed run at ANY sub-mesh width."""
    from pyabc_tpu.serving.tenant import _build_gaussian

    built = _build_gaussian(spec_for(seed))
    observed = built.pop("observed")
    abc = pt.ABCSMC(population_size=pop, seed=seed, fused_generations=G,
                    sharded=sharded, **built)
    abc.new(db, observed, store_sum_stats=True)
    return abc.run(max_nr_populations=gens)


def wait_terminal(tenants, timeout_s: float = 300.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if all(t.state in (COMPLETED, FAILED, CANCELLED, DRAINED)
               for t in tenants):
            return
        time.sleep(0.1)
    raise AssertionError(
        f"tenants not terminal after {timeout_s}s: "
        f"{[(t.id, t.state) for t in tenants]}"
    )


def assert_history_parity(db_a: str, db_b: str, gens: int) -> None:
    """Bit-identical trajectories: epsilon trail + per-generation
    sorted thetas and weights."""
    ha, hb = History(db_a), History(db_b)
    assert ha.n_populations == hb.n_populations == gens
    eps_a = ha.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps_b = hb.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert np.array_equal(eps_a, eps_b), (eps_a, eps_b)
    for t in range(gens):
        df_a, w_a = ha.get_distribution(0, t)
        df_b, w_b = hb.get_distribution(0, t)
        assert np.array_equal(np.sort(df_a["theta"].to_numpy()),
                              np.sort(df_b["theta"].to_numpy())), t
        assert np.array_equal(np.sort(w_a), np.sort(w_b)), t
    ha.close()
    hb.close()


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    uninstall_fault_plan()
    yield
    uninstall_fault_plan()


@pytest.fixture
def make_scheduler(tmp_path):
    """Scheduler factory with guaranteed shutdown (no leaked pumps)."""
    scheds = []

    def make(**kw):
        kw.setdefault("base_dir", str(tmp_path / f"serve{len(scheds)}"))
        kw.setdefault("lease_timeout_s", 60.0)
        s = RunScheduler(**kw)
        scheds.append(s)
        return s

    yield make
    for s in scheds:
        s.shutdown()


# ===================================================== chaos isolation
def test_chaos_isolation_killed_tenant_never_harms_survivors(
        make_scheduler, tmp_path):
    """THE acceptance criterion: tenant A killed hard at EVERY chunk
    (every generation it ever processes), tenants B and C complete with
    posteriors bit-identical to their seed-matched solo runs. A's
    containment: it fails loudly after the requeue budget, with its
    lease history on record — and nothing else in the process notices.
    """
    sched = make_scheduler(n_slots=2, lease_timeout_s=60.0,
                           max_requeues=2)
    install_fault_plan(FaultPlan([
        # kill on every single chunk-processing attempt of the chaos
        # tenant, forever — it can never make progress
        FaultRule(site="orchestrator.chunk", kind="kill", every=1,
                  max_fires=None, match="chaos"),
    ]))
    chaos = sched.submit(spec_for(seed=101), tenant_id="tenant-chaos")
    surv1 = sched.submit(spec_for(seed=7), tenant_id="tenant-s1")
    surv2 = sched.submit(spec_for(seed=8), tenant_id="tenant-s2")
    wait_terminal([chaos, surv1, surv2])
    uninstall_fault_plan()

    # survivors: completed, full schedule
    assert surv1.state == COMPLETED, (surv1.state, surv1.error)
    assert surv2.state == COMPLETED, (surv2.state, surv2.error)
    assert surv1.result["n_populations"] == GENS
    assert surv2.result["n_populations"] == GENS

    # chaos tenant: contained, typed, with its lease trail
    assert chaos.state == FAILED
    assert chaos.requeues == sched.max_requeues
    kinds = [e["kind"] for e in chaos.events_since(0)]
    assert "lease_reaped" in kinds and "requeued" in kinds

    # round 22: the failure left a parseable flight file whose timeline
    # covers the whole fault window — detection (lease_reaped) through
    # requeue to the terminal failure
    from pyabc_tpu.observability import read_flight, render_timeline

    payload = read_flight(chaos.flight_path)
    assert payload["run_id"] == chaos.id
    assert payload["reason"].startswith("finish:")
    ev_kinds = [e["kind"] for e in payload["events"]]
    assert "lease_reaped" in ev_kinds and "requeued" in ev_kinds
    assert FAILED in ev_kinds
    note_kinds = [e["kind"] for e in payload["entries"]]
    assert "lease_reaped" in note_kinds and "finish" in note_kinds
    text = render_timeline(payload)
    assert "lease_reaped" in text and "requeued" in text

    # posterior parity vs seed-matched solo runs — bit-identical
    ref1 = f"sqlite:///{tmp_path}/ref1.db"
    ref2 = f"sqlite:///{tmp_path}/ref2.db"
    solo_reference(7, ref1)
    solo_reference(8, ref2)
    assert_history_parity(surv1.db_path, ref1, GENS)
    assert_history_parity(surv2.db_path, ref2, GENS)


def test_chaos_nan_poison_recovers_in_domain(make_scheduler, tmp_path):
    """A NaN-poisoned tenant (PR-6 silent numerical corruption at
    device.carry) RECOVERS inside its own fault domain — rollback +
    redispatch — and still completes; its neighbor's posterior stays
    bit-identical to the solo baseline. One tenant's numerics never
    bleed into another's."""
    sched = make_scheduler(n_slots=2)
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="nan_poison", after=1,
                  max_fires=1, match="poison"),
    ]))
    poisoned = sched.submit(spec_for(seed=21), tenant_id="tenant-poison")
    clean = sched.submit(spec_for(seed=22), tenant_id="tenant-clean")
    wait_terminal([poisoned, clean])
    uninstall_fault_plan()

    assert poisoned.state == COMPLETED, (poisoned.state, poisoned.error)
    assert clean.state == COMPLETED, (clean.state, clean.error)
    # the poison actually landed in the poisoned tenant's namespace:
    # its private metrics carry the health rollback, the clean
    # tenant's carry none
    p_m = poisoned.metrics.snapshot()
    c_m = clean.metrics.snapshot()
    assert p_m.get("pyabc_tpu_health_events_total", 0) >= 1
    assert c_m.get("pyabc_tpu_health_events_total", 0) == 0
    # neighbor parity vs solo
    ref = f"sqlite:///{tmp_path}/ref_clean.db"
    solo_reference(22, ref)
    assert_history_parity(clean.db_path, ref, GENS)
    # PR-6 contract carried into serving: the poisoned run's RECOVERED
    # posterior is itself bit-identical to its solo baseline
    ref_p = f"sqlite:///{tmp_path}/ref_poison.db"
    solo_reference(21, ref_p)
    assert_history_parity(poisoned.db_path, ref_p, GENS)


# ============================================ lease-expiry requeue (run)
def test_killed_orchestrator_requeues_and_resumes_bit_identical(
        make_scheduler, tmp_path, store_scheme):
    """Satellite: a tenant killed ONCE mid-chunk dies hard (no report);
    the scheduler discovers the dead thread, reclaims the slot,
    requeues the tenant, and the resumed attempt adopts the PR-5
    checkpoint — the final History bit-identical to an uninterrupted
    seed-matched run.

    Parameterized over BOTH History backends (round 17): the columnar
    tenant's requeue-resume must read its adaptive state back through
    the Parquet files and end bit-identical to a ROW-store solo
    reference — the cross-store parity contract."""
    store = "columnar" if "columnar" in store_scheme else "rows"
    sched = make_scheduler(n_slots=1, max_requeues=1)
    install_fault_plan(FaultPlan([
        # fire on the SECOND chunk-processing of the victim (after one
        # full chunk persisted + checkpointed), once
        FaultRule(site="orchestrator.chunk", kind="kill", after=1,
                  max_fires=1, match="victim"),
    ]))
    victim = sched.submit(spec_for(seed=31, gens=8, store=store),
                          tenant_id="tenant-victim")
    wait_terminal([victim])
    uninstall_fault_plan()

    assert victim.state == COMPLETED, (victim.state, victim.error)
    if store == "columnar":
        # the tenant db URL is self-describing: every re-open (the
        # resume load() above, the parity read below) picks the store
        # from the scheme alone
        assert victim.db_path.startswith("sqlite+columnar:///")
    assert victim.requeues == 1 and victim.attempt == 2
    kinds = [e["kind"] for e in victim.events_since(0)]
    assert "lease_reaped" in kinds and "requeued" in kinds

    ref = f"sqlite:///{tmp_path}/ref_victim.db"
    solo_reference(31, ref, gens=8)
    assert_history_parity(victim.db_path, ref, 8)
    # each generation persisted exactly once (resume pruned, no doubles)
    h = History(victim.db_path)
    pops = h.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(pops) == sorted(set(pops)) == list(range(8))
    h.close()


def test_requeue_budget_exhaustion_fails_with_trail(make_scheduler):
    """A tenant that dies on every attempt fails TERMINALLY (typed,
    with its event trail) instead of cycling forever."""
    sched = make_scheduler(n_slots=1, max_requeues=1)
    install_fault_plan(FaultPlan([
        FaultRule(site="orchestrator.chunk", kind="kill", every=1,
                  max_fires=None, match="doomed"),
    ]))
    doomed = sched.submit(spec_for(seed=41), tenant_id="tenant-doomed")
    wait_terminal([doomed])
    uninstall_fault_plan()
    assert doomed.state == FAILED
    assert "requeue budget exhausted" in (doomed.error or "")
    assert doomed.requeues == 1


# ======================================================= admission
def test_admission_backpressure_is_typed_and_bounded(make_scheduler):
    sched = make_scheduler(n_slots=1, max_queued=1)
    t1 = sched.submit(spec_for(seed=51, gens=8))
    # wait until t1 holds the slot so the queue occupancy is exact
    t0 = time.monotonic()
    while t1.state == "queued" and time.monotonic() - t0 < 60:
        time.sleep(0.02)
    t2 = sched.submit(spec_for(seed=52))
    with pytest.raises(AdmissionRejectedError) as exc_info:
        sched.submit(spec_for(seed=53))
    err = exc_info.value
    assert err.retry_after_s is not None and err.retry_after_s >= 1.0
    assert sched.admission.rejected_total == 1
    wait_terminal([t1, t2])
    assert t1.state == COMPLETED and t2.state == COMPLETED


def test_invalid_spec_rejected_without_retry_hint(make_scheduler):
    sched = make_scheduler(n_slots=1)
    with pytest.raises(AdmissionRejectedError) as exc_info:
        sched.submit(TenantSpec(model="no-such-model"))
    assert exc_info.value.retry_after_s is None
    with pytest.raises(AdmissionRejectedError):
        # reserved override: the scheduler owns the tracer binding
        sched.submit(spec_for(seed=1, abcsmc_overrides={"tracer": None}))


# ========================================================== drain
def test_drain_flushes_and_final_checkpoints_every_tenant(
        make_scheduler):
    """SIGTERM semantics: drain() stops admission, every RUNNING tenant
    takes the PR-6 GracefulShutdown path (History flushed + final
    checkpoint written) and lands DRAINED."""
    sched = make_scheduler(n_slots=2)
    a = sched.submit(spec_for(seed=61, gens=40), tenant_id="tenant-da")
    b = sched.submit(spec_for(seed=62, gens=40), tenant_id="tenant-db")
    # wait for real progress so there is a carry to checkpoint
    t0 = time.monotonic()
    while ((a.generations_done < 2 or b.generations_done < 2)
           and time.monotonic() - t0 < 120):
        time.sleep(0.05)
    assert a.generations_done >= 2 and b.generations_done >= 2
    summary = sched.drain(timeout_s=60.0)
    assert summary["forced"] == []
    # both live tenants drained (a fast run may legitimately have
    # completed in the race window)
    for t in (a, b):
        assert t.state in (DRAINED, COMPLETED), (t.id, t.state, t.error)
    drained = [t for t in (a, b) if t.state == DRAINED]
    assert drained, "drain raced both tenants to completion"
    for t in drained:
        # final checkpoint on disk, History flushed and readable
        assert os.path.exists(t.checkpoint_path), t.id
        h = History(t.db_path)
        assert h.n_populations >= 2
        h.close()
    # admission is closed while draining
    with pytest.raises(AdmissionRejectedError):
        sched.submit(spec_for(seed=63))


def test_cancel_queued_and_running(make_scheduler):
    sched = make_scheduler(n_slots=1)
    run = sched.submit(spec_for(seed=71, gens=40), tenant_id="tenant-r")
    queued = sched.submit(spec_for(seed=72), tenant_id="tenant-q")
    assert sched.cancel("tenant-q") is True
    assert queued.state == CANCELLED
    t0 = time.monotonic()
    while run.generations_done < 2 and time.monotonic() - t0 < 120:
        time.sleep(0.05)
    assert sched.cancel("tenant-r") is True
    wait_terminal([run])
    assert run.state == CANCELLED
    assert sched.cancel("tenant-r") is False  # terminal: no-op
    assert sched.cancel("nope") is False


# ============================================ observability namespacing
def test_two_interleaved_runs_keep_separate_namespaces(make_scheduler):
    """Satellite: the pre-round-14 global-state collision, regressed.
    Two tenants run CONCURRENTLY; their spans/metrics land in their own
    namespaces (observability_snapshot()['tenants']), racing snapshot
    readers never error, and neither tenant's series leak into the
    other's."""
    sched = make_scheduler(n_slots=2)
    errors: list = []
    stop = threading.Event()

    def hammer():
        # concurrent snapshot readers while both runs mutate state
        try:
            while not stop.is_set():
                snap = observability_snapshot()
                json.dumps(snap, default=str)  # JSON-ready, always
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    readers = [threading.Thread(target=hammer) for _ in range(2)]
    for r in readers:
        r.start()
    ta = sched.submit(spec_for(seed=81), tenant_id="tenant-a")
    tb = sched.submit(spec_for(seed=82), tenant_id="tenant-b")
    wait_terminal([ta, tb])
    stop.set()
    for r in readers:
        r.join()
    assert not errors, errors
    assert ta.state == COMPLETED and tb.state == COMPLETED

    snap = observability_snapshot()["tenants"]
    assert "tenant-a" in snap and "tenant-b" in snap
    for tid in ("tenant-a", "tenant-b"):
        by_name = snap[tid]["tracer"]["spans_by_name"]
        # a full run's span families, private to the namespace
        assert "chunk" in by_name and "run" in by_name
        assert by_name["run"]["count"] == 1  # ONE run here, never two
    # namespace content matches the tenant's private tracer exactly
    assert snap["tenant-a"]["tracer"] == ta.tracer.snapshot()
    # private metrics: each namespace carries its own syncs_per_run
    assert "pyabc_tpu_syncs_per_run" in snap["tenant-a"]["metrics"]
    assert "pyabc_tpu_syncs_per_run" in snap["tenant-b"]["metrics"]


def test_prometheus_text_tenant_labels():
    """The exporter half of the collision fix: one scrape can carry two
    runs' registries as label-disambiguated series."""
    from pyabc_tpu.observability.export import prometheus_text
    from pyabc_tpu.observability.metrics import MetricsRegistry

    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.gauge("pyabc_tpu_syncs_per_run", "syncs").set(3)
    rb.gauge("pyabc_tpu_syncs_per_run", "syncs").set(5)
    text = (prometheus_text(ra, labels={"tenant": "a"})
            + prometheus_text(rb, labels={"tenant": "b"}))
    assert 'pyabc_tpu_syncs_per_run{tenant="a"} 3' in text
    assert 'pyabc_tpu_syncs_per_run{tenant="b"} 5' in text


# ============================================= kernel cache / zero compile
def test_repeat_shape_tenant_pays_zero_compile(make_scheduler):
    """Acceptance criterion: tenant k+1 with a seen program shape
    adopts the cached compiled context — kernel-cache hit, and NOT ONE
    compile-marked dispatch span in its namespace."""
    sched = make_scheduler(n_slots=1)  # sequential: t1 registers, t2 hits
    t1 = sched.submit(spec_for(seed=91))
    t2 = sched.submit(spec_for(seed=92))
    wait_terminal([t1, t2])
    assert t1.state == COMPLETED and t2.state == COMPLETED
    assert t1.kernel_cache_hit is False
    assert t2.kernel_cache_hit is True
    assert t2.compile_span_count() == 0, (
        "a repeat-shape tenant paid a kernel compile")
    stats = sched.kernel_cache.stats()
    assert stats["hits"] >= 1 and stats["entries"] >= 1


def _prepared_gaussian(tmp_path, tag: str, *, noise_sd=0.5, x_obs=1.0,
                       p=2.0, prior_sd=1.0):
    """A prepared (new()'d, never run) gaussian ABCSMC — what the
    kernel cache sees at adopt time — with every knob the builder can
    bake into the traced closure exposed."""
    from pyabc_tpu.serving.tenant import _build_gaussian

    built = _build_gaussian(spec_for(
        seed=0, params={"noise_sd": noise_sd, "x_obs": x_obs}))
    built["distance_function"] = pt.PNormDistance(p=p)
    built["parameter_priors"] = pt.Distribution(
        theta=pt.RV("norm", 0.0, prior_sd))
    observed = built.pop("observed")
    abc = pt.ABCSMC(population_size=POP, seed=0, fused_generations=G,
                    **built)
    abc.new(f"sqlite:///{tmp_path}/key_{tag}.db", observed)
    return abc


def test_program_shape_key_sees_closure_and_config(tmp_path):
    """The isolation-contract regression the name-only key violated:
    two tenants with the SAME x_obs but different noise_sd (or prior
    scale, or distance p) trace to DIFFERENT compiled programs — equal
    keys would silently hand tenant B tenant A's kernels and bit-wrong
    posteriors. Identical configs still collapse to one key (the
    zero-compile hit path)."""
    from pyabc_tpu.utils.xla_cache import program_shape_key

    base = program_shape_key(_prepared_gaussian(tmp_path, "a"))
    same = program_shape_key(_prepared_gaussian(tmp_path, "b"))
    assert base == same  # seed/db differ, program shape does not

    varied = {
        "noise_sd": _prepared_gaussian(tmp_path, "n", noise_sd=0.7),
        "x_obs": _prepared_gaussian(tmp_path, "x", x_obs=2.0),
        "distance p": _prepared_gaussian(tmp_path, "p", p=1.0),
        "prior scale": _prepared_gaussian(tmp_path, "s", prior_sd=3.0),
    }
    for what, abc in varied.items():
        assert program_shape_key(abc) != base, (
            f"key blind to {what}: cross-tenant kernel adoption would "
            f"compute the wrong posterior")


def test_jax_model_content_hash_distinguishes_closures():
    """Model identity is the traced closure, not the display name."""
    from pyabc_tpu.serving.tenant import _build_gaussian

    a = _build_gaussian(spec_for(seed=1, params={"noise_sd": 0.5}))
    b = _build_gaussian(spec_for(seed=2, params={"noise_sd": 0.5}))
    c = _build_gaussian(spec_for(seed=1, params={"noise_sd": 0.9}))
    assert a["models"].name == c["models"].name == "gauss"
    assert a["models"].content_hash() == b["models"].content_hash()
    assert a["models"].content_hash() != c["models"].content_hash()


# ============================================ scheduler hygiene regressions
def test_cancel_before_run_handle_exists_lands_cancelled(
        make_scheduler, monkeypatch):
    """A cancel acknowledged while the attempt thread is still building
    (tenant.abc is None) must stop the run once the handle exists —
    not let it proceed to COMPLETED despite the ack."""
    gate = threading.Event()
    building = threading.Event()
    orig = TenantSpec.abcsmc_kwargs

    def slow_build(self):
        building.set()
        assert gate.wait(60)
        return orig(self)

    monkeypatch.setattr(TenantSpec, "abcsmc_kwargs", slow_build)
    sched = make_scheduler(n_slots=1)
    t = sched.submit(spec_for(seed=501), tenant_id="tenant-precancel")
    assert building.wait(60)
    assert t.state == RUNNING and t.abc is None
    assert sched.cancel("tenant-precancel") is True
    gate.set()
    wait_terminal([t])
    assert t.state == CANCELLED, (t.state, t.error)


def test_drain_times_out_on_wall_clock_under_injected_clock(
        make_scheduler):
    """drain()'s deadline must advance on WALL time: with a manually-
    stepped fake clock (the resilience-test pattern) and a hung RUNNING
    tenant, a clock-based deadline never fires and drain spins forever
    instead of reporting the tenant forced."""
    from pyabc_tpu.serving.tenant import Tenant

    class ManualClock:
        def __init__(self):
            self.t = 100.0

        def now(self):
            return self.t

    sched = make_scheduler(clock=ManualClock())
    hung = Tenant("tenant-hung", spec_for(seed=1), clock=sched.clock,
                  db_path="sqlite:///:memory:",
                  checkpoint_path=os.devnull)
    hung.state = RUNNING
    with sched._lock:
        sched._tenants[hung.id] = hung
    t0 = time.monotonic()
    summary = sched.drain(timeout_s=0.5)
    assert time.monotonic() - t0 < 30, "drain ignored its timeout"
    assert summary["forced"] == ["tenant-hung"]
    with sched._lock:  # let shutdown proceed cleanly
        del sched._tenants[hung.id]


def test_terminal_tenants_evicted_beyond_retention_cap(make_scheduler):
    """A long-lived serving process must not grow with every tenant it
    ever finished: beyond max_terminal_tenants the oldest terminal
    records (and their observability namespaces) are evicted, and
    run-lease reaps leave no slot ranges behind in the lease table."""
    sched = make_scheduler(n_slots=1, max_queued=8,
                           max_terminal_tenants=2)
    runner = sched.submit(spec_for(seed=511, gens=40),
                          tenant_id="tenant-evict-run")
    cancelled = [
        sched.submit(spec_for(seed=512 + i), tenant_id=f"tenant-ev{i}")
        for i in range(4)
    ]
    for t in cancelled:
        assert sched.cancel(t.id) is True
        assert t.state == CANCELLED
    # newest two terminal records retained, oldest two evicted
    assert sched.get("tenant-ev0") is None
    assert sched.get("tenant-ev1") is None
    assert sched.get("tenant-ev2") is not None
    assert sched.get("tenant-ev3") is not None
    snap = observability_snapshot()["tenants"]
    assert "tenant-ev0" not in snap and "tenant-ev1" not in snap
    sched.cancel("tenant-evict-run")
    wait_terminal([runner])
    assert sched.leases.stats()["requeued_slots"] == 0


def test_lease_table_discard_requeued():
    """Run-level leases never redispatch slot ranges; discarding after
    a reap keeps the table bounded."""
    from pyabc_tpu.resilience.lease import LeaseTable

    class Clock:
        t = 0.0

        def now(self):
            return self.t

    clock = Clock()
    table = LeaseTable(clock, timeout_s=1.0)
    table.grant("tenant-x", 0, 1)
    clock.t = 5.0
    events = table.reap(clock.now())
    assert len(events) == 1
    assert table.stats()["requeued_slots"] == 1
    assert table.discard_requeued() == 1
    assert table.stats()["requeued_slots"] == 0


# ====================================================== writer pool
def test_writer_pool_preserves_order_and_flush():
    pool = WriterPool(n_threads=2)
    try:
        out_a: list = []
        out_b: list = []
        ha = pool.handle()
        hb = pool.handle()
        for i in range(50):
            ha.submit(out_a.append, ("a", i))
            hb.submit(out_b.append, ("b", i))
        ha.flush()
        hb.flush()
        assert out_a == [("a", i) for i in range(50)]
        assert out_b == [("b", i) for i in range(50)]
    finally:
        pool.close()


def test_writer_pool_sticky_error_isolated_per_handle():
    """One tenant's dead db latches ONLY its own handle; the shared
    pool keeps serving every other tenant's stream."""
    pool = WriterPool(n_threads=1)  # ONE shared thread: worst case
    try:
        install_fault_plan(FaultPlan([
            FaultRule(site="history.persist", kind="error", max_fires=1,
                      match="tenant-bad"),
        ]))
        good_rows: list = []
        bad = pool.handle(scope_tag="tenant-bad")
        good = pool.handle(scope_tag="tenant-good")
        bad.submit(good_rows.append, "b0")   # dies here (injected)
        bad.submit(good_rows.append, "b1")   # drained unexecuted
        for i in range(5):
            good.submit(good_rows.append, f"g{i}")
        good.flush()  # the good stream is unaffected
        assert [r for r in good_rows if r.startswith("g")] == \
            [f"g{i}" for i in range(5)]
        with pytest.raises(InjectedPersistError):
            bad.flush()
        # sticky: later submits re-raise too
        with pytest.raises(InjectedPersistError):
            bad.submit(good_rows.append, "b2")
        assert "b1" not in good_rows and "b2" not in good_rows
    finally:
        uninstall_fault_plan()
        pool.close()


def test_fault_scope_is_thread_local():
    assert current_fault_scope() == ""
    seen = {}
    with fault_scope("outer"):
        assert current_fault_scope() == "outer"
        with fault_scope("inner"):
            assert current_fault_scope() == "inner"
        assert current_fault_scope() == "outer"

        def child():
            seen["tag"] = current_fault_scope()

        th = threading.Thread(target=child)
        th.start()
        th.join()
    # spawned threads do NOT inherit the scope: a tenant's domain is
    # its orchestrator thread
    assert seen["tag"] == ""
    assert current_fault_scope() == ""


# =========================================================== HTTP API
def test_api_submit_status_stream_metrics(make_scheduler):
    sched = make_scheduler(n_slots=1, max_queued=1)
    httpd = serve_api(sched, port=0, block=False)
    port = httpd.server_port
    base = f"http://127.0.0.1:{port}"

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=json.dumps(body).encode(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)

    try:
        st, sub, _ = post("/api/submit", spec_for(seed=201).to_dict())
        assert st == 200 and sub["id"]
        tid = sub["id"]
        # malformed spec -> 400
        st, err, _ = post("/api/submit", {"model": "nope"})
        assert st == 400 and "invalid spec" in err["error"]

        tenant = sched.get(tid)
        wait_terminal([tenant])
        with urllib.request.urlopen(f"{base}/api/tenant/{tid}",
                                    timeout=30) as r:
            status = json.loads(r.read())
        assert status["state"] == COMPLETED
        assert status["generations_done"] == GENS

        # stream: full NDJSON event tail, terminated by an end record
        with urllib.request.urlopen(f"{base}/api/tenant/{tid}/stream",
                                    timeout=30) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines() if ln]
        kinds = [ev["kind"] for ev in lines]
        assert kinds[0] == "admitted" and kinds[-1] == "end"
        assert "chunk" in kinds and COMPLETED in kinds

        # scheduler + tenants snapshot
        with urllib.request.urlopen(f"{base}/api/tenants",
                                    timeout=30) as r:
            snap = json.loads(r.read())
        assert snap["n_slots"] == 1
        assert any(t["id"] == tid for t in snap["tenants"])

        # observability endpoint aggregates the tenant namespace —
        # and (round 22) carries the registered SLO engines' block
        with urllib.request.urlopen(f"{base}/api/observability",
                                    timeout=30) as r:
            obs = json.loads(r.read())
        assert tid in obs["tenants"]
        assert "slo" in obs and "federation" in obs

        # on-demand flight snapshot (round 22): the live rings, no
        # fault needed
        with urllib.request.urlopen(f"{base}/api/tenant/{tid}/flight",
                                    timeout=30) as r:
            flight = json.loads(r.read())
        assert flight["run_id"] == tid and flight["reason"] == "api"
        assert any(e["kind"] == "admitted" for e in flight["events"])

        # /metrics: global families + tenant-labelled series
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert "pyabc_tpu_tenant_live" in text
        assert f'tenant="{tid}"' in text

        # unknown tenant -> 404
        try:
            urllib.request.urlopen(f"{base}/api/tenant/ghost", timeout=30)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        httpd.shutdown()


def test_api_backpressure_is_http_429_with_retry_after(make_scheduler):
    sched = make_scheduler(n_slots=1, max_queued=1)
    httpd = serve_api(sched, port=0, block=False)
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        t1 = sched.submit(spec_for(seed=211, gens=8))
        t0 = time.monotonic()
        while t1.state == "queued" and time.monotonic() - t0 < 60:
            time.sleep(0.02)
        sched.submit(spec_for(seed=212))  # fills the queue
        req = urllib.request.Request(
            base + "/api/submit",
            data=json.dumps(spec_for(seed=213).to_dict()).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=30)
        e = exc_info.value
        assert e.code == 429
        assert float(e.headers["Retry-After"]) >= 1
        body = json.loads(e.read())
        assert body["retry_after_s"] >= 1.0
        wait_terminal([t1])
    finally:
        httpd.shutdown()


# ================================================ mesh-aware serving (r15)
def test_sharded_tenant_gets_submesh_lease_and_matches_virtual_solo(
        make_scheduler, tmp_path):
    """Tentpole: a ``sharded=4`` tenant maps to a contiguous width-4
    sub-mesh lease (conftest forces 8 CPU devices, so the mesh is
    real), and its posterior is BIT-identical to the seed-matched solo
    virtual-shard run — the PR-9 mesh==virtual contract holding through
    the scheduler's leased path."""
    sched = make_scheduler(n_devices=8)
    t = sched.submit(spec_for(seed=601, sharded=4), tenant_id="t-shard")
    small = sched.submit(spec_for(seed=602), tenant_id="t-small")
    wait_terminal([t, small])
    assert t.state == COMPLETED, (t.state, t.error)
    assert small.state == COMPLETED, (small.state, small.error)
    assert t.widths == [4]
    assert t.to_status()["submesh"] is None  # released on completion
    ref = f"sqlite:///{tmp_path}/ref_shard.db"
    solo_reference(601, ref, sharded=4)
    assert_history_parity(t.db_path, ref, GENS)
    assert sched.allocator.check_invariants() == []
    assert sched.allocator.widest_free() == 8  # coalesced back


def test_sharded_spec_validation():
    with pytest.raises(ValueError):
        spec_for(seed=1, sharded=3).validate()
    with pytest.raises(ValueError):
        spec_for(seed=1, sharded=1).validate()
    spec_for(seed=1, sharded=8).validate()
    # the scheduler owns placement: mesh/sharded overrides are reserved
    with pytest.raises(ValueError):
        spec_for(seed=1, abcsmc_overrides={"mesh": None}).validate()


def test_preempted_tenant_requeues_and_resumes_bit_identical_narrower(
        make_scheduler, tmp_path):
    """Tentpole: checkpoint-preemption. A width-4 tenant is preempted
    at a chunk boundary (graceful stop -> checkpoint), its sub-mesh
    frees (a queued small tenant takes a slice), and it RESUMES on the
    narrower sub-mesh that is left — full History bit-identical to the
    seed-matched uninterrupted solo run, requeue budget untouched."""
    gens = 8
    sched = make_scheduler(n_devices=4)
    big = sched.submit(spec_for(seed=611, gens=gens, sharded=4),
                       tenant_id="t-big")
    t0 = time.monotonic()
    while big.generations_done < 2 and time.monotonic() - t0 < 120:
        time.sleep(0.05)
    assert big.generations_done >= 2
    # no capacity left: the small tenant queues behind the big lease
    small = sched.submit(spec_for(seed=612, gens=4), tenant_id="t-sm")
    assert sched.preempt("t-big") is True
    assert sched.preempt("t-big") is False  # one in-flight preempt
    t0 = time.monotonic()
    while big.preemptions < 1 and time.monotonic() - t0 < 120:
        time.sleep(0.05)
    wait_terminal([big, small])
    assert big.state == COMPLETED, (big.state, big.error)
    assert small.state == COMPLETED, (small.state, small.error)
    assert big.preemptions == 1
    assert big.requeues == 0  # preemption never charges the budget
    kinds = [e["kind"] for e in big.events_since(0)]
    assert "preempt_requested" in kinds and "preempted" in kinds
    # resumed on a DIFFERENT (narrower) width: the small tenant holds a
    # device, so the widest free divisor of 4 was 2
    assert big.widths[0] == 4 and big.widths[1] < 4, big.widths
    # the preempt drain landed as a span in the tenant's namespace
    assert any(sp.name == "preempt.drain"
               for sp in big.tracer.spans())
    ref = f"sqlite:///{tmp_path}/ref_big.db"
    solo_reference(611, ref, gens=gens, sharded=4)
    assert_history_parity(big.db_path, ref, gens)
    # each generation persisted exactly once across the preemption
    h = History(big.db_path)
    pops = h.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(pops) == sorted(set(pops)) == list(range(gens))
    h.close()
    assert sched.allocator.check_invariants() == []


def test_device_loss_shrinks_capacity_and_replaces_on_narrower_width(
        make_scheduler, tmp_path):
    """Tentpole: device-loss survival. An injected ``device_lost`` at
    the polled ``device.mesh`` site kills 6 of 8 devices including the
    running tenant's sub-mesh: its lease is reaped, the allocator
    quarantines the devices (capacity 8 -> 2, admission reprices), and
    the tenant resumes on the surviving width-2 sub-mesh — bit-
    identical to the seed-matched solo run, requeue budget untouched
    (infrastructure faults are not the tenant's fault)."""
    from pyabc_tpu.observability.metrics import FAULTS_INJECTED_TOTAL

    gens = 8
    sched = make_scheduler(n_devices=8, max_requeues=1)
    t = sched.submit(spec_for(seed=621, gens=gens, sharded=4),
                     tenant_id="t-loss")
    t0 = time.monotonic()
    while t.generations_done < 2 and time.monotonic() - t0 < 120:
        time.sleep(0.05)
    assert t.submesh_width == 4 and t.submesh_lo == 0
    from pyabc_tpu.observability import global_metrics

    faults_before = global_metrics().counter(
        FAULTS_INJECTED_TOTAL, "faults fired").value
    install_fault_plan(FaultPlan.parse(
        "device.mesh:device_lost:devices=0-5"))
    t0 = time.monotonic()
    while t.device_loss_requeues < 1 and time.monotonic() - t0 < 60:
        time.sleep(0.05)
    uninstall_fault_plan()
    wait_terminal([t])
    assert t.state == COMPLETED, (t.state, t.error)
    assert t.device_loss_requeues == 1 and t.requeues == 0
    assert t.widths == [4, 2], t.widths  # survivors: devices 6-7
    kinds = [e["kind"] for e in t.events_since(0)]
    assert "device_lost" in kinds
    # the injected topology event counts like every other fault
    assert global_metrics().counter(
        FAULTS_INJECTED_TOTAL, "faults fired").value > faults_before
    # the device-loss recovery span covers loss -> re-placement
    assert any(sp.name == "device_loss.replace"
               for sp in t.tracer.spans())
    # capacity shrank for real: allocator AND admission agree
    assert sched.allocator.healthy_count() == 2
    assert sched.snapshot()["admission"]["n_chips"] == 2
    assert sched.devices_lost_total == 6
    assert sched.allocator.check_invariants() == []
    ref = f"sqlite:///{tmp_path}/ref_loss.db"
    solo_reference(621, ref, gens=gens, sharded=4)
    assert_history_parity(t.db_path, ref, gens)


def test_host_loss_reaps_segment_requeues_budget_free(
        make_scheduler, tmp_path):
    """Round 18 tentpole: HOST-loss survival on a 2-host fleet. An
    injected ``host_lost`` at the polled ``device.mesh`` site kills host
    1 (devices 4-7) under a running tenant: every lease on the segment
    is reaped at once, the segment quarantines (capacity 8 -> 4,
    admission reprices fleet chip-seconds), ``hosts_lost_total`` ticks,
    and the tenant requeues BUDGET-FREE from its checkpoint — finishing
    bit-identical to its seed-matched solo run. The host-0 tenant never
    notices."""
    from pyabc_tpu.observability import global_metrics
    from pyabc_tpu.observability.metrics import HOSTS_LOST_TOTAL

    gens = 8
    sched = make_scheduler(n_devices=8, n_hosts=2, max_requeues=1)
    assert sched.allocator.devices_per_host == 4
    t0 = sched.submit(spec_for(seed=641, gens=gens, sharded=4),
                      tenant_id="t-host0")
    t1 = sched.submit(spec_for(seed=642, gens=gens, sharded=4),
                      tenant_id="t-host1")
    t_start = time.monotonic()
    while ((t0.submesh_lo is None or t1.submesh_lo is None)
           and time.monotonic() - t_start < 60):
        time.sleep(0.02)
    # host-confined placement: one tenant per host segment
    assert {t0.submesh_lo, t1.submesh_lo} == {0, 4}
    victim_on_1 = t0 if t0.submesh_lo == 4 else t1
    # let the victim persist at least one generation first (the requeue
    # then genuinely RESUMES from its History, not from scratch)
    t_start = time.monotonic()
    while (victim_on_1.generations_done < 1
           and time.monotonic() - t_start < 120):
        time.sleep(0.05)
    hosts_before = global_metrics().counter(
        HOSTS_LOST_TOTAL, "hosts lost").value
    plan = install_fault_plan(
        FaultPlan.parse("device.mesh:host_lost:devices=1"))
    t_start = time.monotonic()
    while (victim_on_1.device_loss_requeues < 1
           and time.monotonic() - t_start < 120):
        time.sleep(0.05)
    assert plan.n_fired("device.mesh") == 1, \
        "host_lost fault never applied (scheduler pump starved?)"
    uninstall_fault_plan()
    wait_terminal([t0, t1])
    dead, safe = victim_on_1, (t0 if victim_on_1 is t1 else t1)
    assert dead.state == COMPLETED, (dead.state, dead.error)
    assert safe.state == COMPLETED, (safe.state, safe.error)
    # budget-free: infrastructure loss never eats the tenant's requeues
    assert dead.device_loss_requeues == 1 and dead.requeues == 0
    assert safe.device_loss_requeues == 0
    kinds = [e["kind"] for e in dead.events_since(0)]
    assert "host_lost" in kinds
    host_ev = next(e for e in dead.events_since(0)
                   if e["kind"] == "host_lost")
    assert host_ev["host"] == 1
    # the fleet noticed: counters, allocator books and admission agree
    assert sched.hosts_lost_total == 1
    assert sched.snapshot()["hosts_lost_total"] == 1
    assert global_metrics().counter(
        HOSTS_LOST_TOTAL, "hosts lost").value == hosts_before + 1
    assert sched.allocator.stats()["lost_hosts"] == [1]
    assert sched.allocator.healthy_count() == 4
    assert sched.snapshot()["admission"]["n_chips"] == 4
    assert sched.devices_lost_total == 4
    assert sched.allocator.check_invariants() == []
    # bit-identity for BOTH: the re-placed victim and the bystander
    for tenant, seed in ((t0, 641), (t1, 642)):
        ref = f"sqlite:///{tmp_path}/ref_host_{seed}.db"
        solo_reference(seed, ref, gens=gens, sharded=4)
        assert_history_parity(tenant.db_path, ref, gens)


def test_multi_host_spec_validation_and_width_capping(make_scheduler):
    """TenantSpec.multi_host gatekeeping: straddling a host segment is
    an explicit opt-in (and needs a sharded width to make sense); a
    plain sharded=8 tenant on a 2-host pool is CAPPED to the host
    segment width instead of spanning hosts implicitly."""
    with pytest.raises(ValueError, match="multi_host"):
        TenantSpec(model="gaussian", population_size=100, generations=2,
                   seed=1, multi_host=True).validate()
    spec = spec_for(seed=651, gens=2, sharded=8)
    rt = TenantSpec.from_dict(spec.to_dict())
    assert rt.multi_host is False
    sched = make_scheduler(n_devices=8, n_hosts=2)
    t = sched.submit(spec, tenant_id="t-capped")
    wait_terminal([t])
    assert t.state == COMPLETED, (t.state, t.error)
    # widest host-confined divisor width of sharded=8 on a 4-device
    # segment: 4 — never 8 (that would straddle hosts implicitly)
    assert t.widths and max(t.widths) == 4, t.widths
    assert sched.allocator.check_invariants() == []


def test_cold_start_retry_after_seeded_from_spec(make_scheduler):
    """Satellite: with ZERO completed runs the measured EW average
    does not exist — the first 429s seed their Retry-After from the
    REJECTED spec's own schedule (chunks x default per-chunk price x
    population scale) instead of degenerating."""
    from pyabc_tpu.serving.admission import spec_chip_seconds_estimate

    sched = make_scheduler(n_slots=1, max_queued=0)
    spec = spec_for(seed=631, gens=12, pop=2000)
    assert sched.admission.stats()["cold_start"] is True
    with pytest.raises(AdmissionRejectedError) as exc_info:
        sched.submit(spec)
    est = spec_chip_seconds_estimate(spec)
    # gens=12 / G=2 -> 6 chunks x 2.0 s x (2000/1000) = 24 chip-s
    assert est == pytest.approx(24.0)
    assert exc_info.value.retry_after_s == pytest.approx(est)
    # a bigger spec carries a proportionally bigger honest hint
    with pytest.raises(AdmissionRejectedError) as exc_info2:
        sched.submit(spec_for(seed=632, gens=24, pop=2000))
    assert exc_info2.value.retry_after_s == pytest.approx(2 * est)


def test_admission_prices_chip_seconds_not_queue_position(
        make_scheduler):
    """A completed wide run feeds width x wall seconds into the EW
    average, and device loss reprices the SAME backlog higher."""
    from pyabc_tpu.serving.admission import AdmissionController

    adm = AdmissionController(max_queued=4, n_chips=8)
    adm.note_run_seconds(10.0, chips=4)  # 40 chip-seconds
    assert adm.stats()["avg_chip_s"] == pytest.approx(40.0)
    hint_8 = adm.retry_after_s(3)
    assert hint_8 == pytest.approx(4 * 40.0 / 8)
    adm.set_capacity(2)  # 6 devices lost
    assert adm.retry_after_s(3) == pytest.approx(4 * 40.0 / 2)
    assert adm.retry_after_s(3) > hint_8


def test_api_preempt_endpoint(make_scheduler):
    sched = make_scheduler(n_slots=1)
    httpd = serve_api(sched, port=0, block=False)
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        req = urllib.request.Request(
            base + "/api/tenant/ghost/preempt", data=b"{}",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=30)
        assert exc_info.value.code == 409  # not running: nothing to do
    finally:
        httpd.shutdown()


def test_auto_preemption_frees_capacity_for_starved_queue(
        make_scheduler, tmp_path):
    """The preemption POLICY: with ``preempt_queue_wait_s`` armed, a
    queued tenant that sits unplaceable triggers a checkpoint
    preemption of the widest running tenant; both complete, and the
    preempted tenant's posterior still matches its solo run."""
    gens, pop = 16, 1000  # long enough that the policy beats the run
    sched = make_scheduler(n_devices=2, preempt_queue_wait_s=0.2)
    big = sched.submit(spec_for(seed=641, gens=gens, pop=pop, sharded=2),
                       tenant_id="t-auto-big")
    t0 = time.monotonic()
    while big.generations_done < 2 and time.monotonic() - t0 < 120:
        time.sleep(0.05)
    small = sched.submit(spec_for(seed=642, gens=4),
                         tenant_id="t-auto-sm")
    wait_terminal([big, small])
    assert small.state == COMPLETED, (small.state, small.error)
    assert big.state == COMPLETED, (big.state, big.error)
    assert big.preemptions >= 1
    ref = f"sqlite:///{tmp_path}/ref_auto.db"
    h_ref = solo_reference(641, ref, gens=gens, pop=pop, sharded=2)
    # pop-1000 MedianEpsilon runs legitimately stop early (round
    # budget); parity is over the generations BOTH runs produced
    assert_history_parity(big.db_path, ref, int(h_ref.n_populations))


# ============================================ lifecycle + streaming (r19)
def test_terminal_tenant_eviction_gcs_disk(make_scheduler, store_scheme):
    """Satellite bugfix (round 19): evicting a terminal tenant record
    must also delete its History db (and columnar Parquet files, and
    the checkpoint) — the pre-round-19 eviction dropped the in-memory
    record and leaked the disk forever. Parameterized over both store
    backends so the Parquet sidecar directory is covered too."""
    import pathlib

    from pyabc_tpu.serving.lifecycle import disk_usage

    store = "columnar" if "columnar" in store_scheme else "rows"
    sched = make_scheduler(n_slots=1, max_queued=8,
                           max_terminal_tenants=1)
    tenants = [
        sched.submit(spec_for(seed=711 + i, gens=2, pop=60, store=store),
                     tenant_id=f"tenant-gcdisk{i}")
        for i in range(3)
    ]
    wait_terminal(tenants)
    for t in tenants:
        assert t.state == COMPLETED, (t.id, t.state, t.error)
    # cap 1: the two oldest terminal records were evicted ...
    assert sched.get("tenant-gcdisk0") is None
    assert sched.get("tenant-gcdisk1") is None
    assert sched.get("tenant-gcdisk2") is not None
    # ... and their disk followed them out: db, -wal, Parquet, checkpoint
    for t in tenants[:2]:
        assert t.disposed
        assert disk_usage(t.db_path)["total"] == 0
        assert not os.path.exists(t.checkpoint_path)
    assert disk_usage(tenants[2].db_path)["total"] > 0
    base = pathlib.Path(sched.base_dir)
    owners = {p.name.split(".")[0] for p in base.iterdir()}
    assert "tenant-gcdisk0" not in owners
    assert "tenant-gcdisk1" not in owners
    assert sched.lifecycle.stats()["tenants_disposed_total"] >= 2


def test_eviction_defers_while_stale_attempt_thread_alive(make_scheduler):
    """Disposal must NOT race a still-unwinding attempt thread: a reaped
    or cancelled tenant's thread stops only at its next chunk boundary,
    and a History write checking out a fresh sqlite connection AFTER the
    unlink recreates the db as an orphan file (observed in the round-19
    traffic lane). Eviction therefore defers while ``tenant.thread`` is
    alive and the pump retries once the thread exits."""
    from pyabc_tpu.serving.lifecycle import disk_usage

    sched = make_scheduler(n_slots=1, max_terminal_tenants=1)
    a = sched.submit(spec_for(seed=741, gens=2, pop=60),
                     tenant_id="tenant-defer0")
    wait_terminal([a])
    assert a.state == COMPLETED, (a.state, a.error)
    # stand in for a stale attempt still unwinding (the real thread has
    # exited; eviction only looks at liveness)
    release = threading.Event()
    th = threading.Thread(target=release.wait, daemon=True)
    th.start()
    a.thread = th
    try:
        b = sched.submit(spec_for(seed=742, gens=2, pop=60),
                         tenant_id="tenant-defer1")
        wait_terminal([b])
        # b's finish overflowed the cap-1 ring, but a's "attempt" is
        # alive: eviction defers — record kept, files untouched
        time.sleep(0.5)
        assert sched.get("tenant-defer0") is not None
        assert not a.disposed
        assert disk_usage(a.db_path)["total"] > 0
    finally:
        release.set()
    th.join(timeout=10)
    # thread gone -> the pump's retry disposes on a later tick
    t0 = time.monotonic()
    while time.monotonic() - t0 < 10:
        if sched.get("tenant-defer0") is None:
            break
        time.sleep(0.05)
    assert sched.get("tenant-defer0") is None
    assert a.disposed
    assert disk_usage(a.db_path)["total"] == 0


def test_stream_posterior_live_parity_both_stores(make_scheduler,
                                                  store_scheme):
    """Tentpole (round 19): the live posterior stream — Arrow IPC when
    pyarrow is present, NDJSON summary lines otherwise — reconstructs
    the epsilon trail + per-generation posterior means BIT-identical to
    a post-hoc History read, on both store backends. The client opens
    the stream while the run is LIVE; the server pushes each generation
    as it lands and ends the stream at the terminal state."""
    from pyabc_tpu.serving.streaming import (
        generation_summaries,
        parse_summary_lines,
        stream_posterior,
    )
    from pyabc_tpu.storage.columnar import has_pyarrow

    store = "columnar" if "columnar" in store_scheme else "rows"
    sched = make_scheduler(n_slots=1)
    httpd = serve_api(sched, port=0, block=False)
    port = httpd.server_port
    try:
        t = sched.submit(spec_for(seed=721, gens=4, store=store),
                         tenant_id="tenant-stream")
        # consume LIVE: blocks following the run, ends at terminal
        fmt, streamed = stream_posterior("127.0.0.1", port,
                                         "tenant-stream", timeout_s=240)
        wait_terminal([t])
        assert t.state == COMPLETED, (t.state, t.error)
        posthoc = generation_summaries(t.db_path)
        assert [s["t"] for s in posthoc] == list(range(4))
        assert streamed == posthoc  # float64 survives the wire exactly
        assert fmt == ("arrow" if has_pyarrow() else "ndjson")
        # the explicit NDJSON fallback a pyarrow-less CLIENT requests
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/tenant/tenant-stream"
                "/stream?format=summaries", timeout=60) as r:
            assert r.headers["Content-Type"].startswith(
                "application/x-ndjson")
            lines = [ln for ln in r.read().decode().splitlines()
                     if ln.strip()]
        assert parse_summary_lines(lines) == posthoc
    finally:
        httpd.shutdown()


def test_requeue_resume_survives_retention_gc(make_scheduler, tmp_path):
    """Lifecycle safety (round 19): retention GC never deletes what a
    resume needs. A keep-last-2 sweep runs every 0.1 s around a tenant
    killed once mid-run (after 2 chunks = 4 persisted generations, so
    the sweep has prunable history before the resume); the requeued
    attempt adopts the checkpoint, completes, and every generation the
    pruned History still holds is bit-identical to a solo reference."""
    from pyabc_tpu.serving.lifecycle import RetentionPolicy

    sched = make_scheduler(n_slots=1, max_requeues=1,
                           retention=RetentionPolicy(keep_last_k=2),
                           lifecycle_sweep_s=0.1)
    install_fault_plan(FaultPlan([
        FaultRule(site="orchestrator.chunk", kind="kill", after=2,
                  max_fires=1, match="victim"),
    ]))
    victim = sched.submit(spec_for(seed=731, gens=8),
                          tenant_id="tenant-gc-victim")
    wait_terminal([victim])
    uninstall_fault_plan()
    assert victim.state == COMPLETED, (victim.state, victim.error)
    assert victim.requeues == 1 and victim.attempt == 2
    # the post-terminal sweep prunes the idle db down to keep_last_k
    t0 = time.monotonic()
    n = -1
    while time.monotonic() - t0 < 30:
        h = History(victim.db_path)
        n = int(h.n_populations)
        h.close()
        if n <= 2:
            break
        time.sleep(0.1)
    assert n == 2, n
    assert sched.lifecycle.stats()["generations_gced_total"] > 0
    # surviving generations bit-identical to the solo reference's tail
    ref = f"sqlite:///{tmp_path}/ref_gcresume.db"
    solo_reference(731, ref, gens=8)
    h, href = History(victim.db_path), History(ref)
    try:
        pops = h.get_all_populations().query("t >= 0")
        ref_pops = href.get_all_populations().query("t >= 0")
        ref_eps = {int(r["t"]): float(r["epsilon"])
                   for _, r in ref_pops.iterrows()}
        assert sorted(int(r["t"]) for _, r in pops.iterrows()) == [6, 7]
        for _, row in pops.iterrows():
            t = int(row["t"])
            assert float(row["epsilon"]) == ref_eps[t]
            df_a, w_a = h.get_distribution(0, t)
            df_b, w_b = href.get_distribution(0, t)
            assert np.array_equal(np.sort(df_a["theta"].to_numpy()),
                                  np.sort(df_b["theta"].to_numpy())), t
            assert np.array_equal(np.sort(w_a), np.sort(w_b)), t
    finally:
        h.close()
        href.close()


# ======================================================== fairness sanity
def test_slots_rotate_through_queue_no_starvation(make_scheduler):
    """More tenants than slots: every tenant eventually runs and
    completes (FIFO slot handout, no head-of-line pathologies)."""
    sched = make_scheduler(n_slots=2, max_queued=8)
    tenants = [sched.submit(spec_for(seed=300 + i, gens=4))
               for i in range(5)]
    wait_terminal(tenants)
    for t in tenants:
        assert t.state == COMPLETED, (t.id, t.state, t.error)
        assert t.result["n_populations"] == 4
