"""Speculative look-ahead rounds for configs the fused chunks can't take.

The reference's redis look-ahead (SURVEY.md §2.3): start generation t+1
work before generation t's bookkeeping is finished. Here: as soon as the
transitions are refit on population t, a FULL eps=+inf proposal round for
t+1 is dispatched to the device; acceptance is applied on the host once
the slow strategy updates fixed the real threshold/temperature (delayed
evaluation). Proposals are drawn from the FINAL t+1 proposal density, so
weights need no correction.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.epsilon.temperature import DalyScheme

NOISE_SD = 0.4
X_OBS = 0.8


def _model():
    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    return model


def _noisy_daly(seed):
    """fused_generations=1 (user opt-out; Daly itself now has a device
    twin) -> pipelined per-generation loop with speculation."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(
        _model(), prior, pt.IndependentNormalKernel(var=[NOISE_SD**2]),
        population_size=400,
        eps=pt.Temperature(schemes=[DalyScheme()],
                           initial_temperature=32.0),
        acceptor=pt.StochasticAcceptor(), seed=seed,
        fused_generations=1,
    )


def _local_transition(seed, pipeline=True):
    """fused_generations=1 (LocalTransition itself now refits in-kernel)
    -> per-generation loop; pipeline toggles the speculative look-ahead."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return pt.ABCSMC(
        model, prior, pt.PNormDistance(p=2), population_size=300,
        eps=pt.MedianEpsilon(),
        transitions=pt.LocalTransition(), seed=seed, pipeline=pipeline,
        fused_generations=1,
    )


def exact_posterior():
    var = 1.0 / (1.0 + 1 / NOISE_SD**2)
    return var * X_OBS / NOISE_SD**2, np.sqrt(var)


def test_daly_config_speculates_and_recovers_posterior():
    abc = _noisy_daly(seed=9)
    assert not abc._fused_chunk_capable()  # fused_generations=1 opt-out
    abc.speculation_min_adapt_s = 0.0  # force the auto-gate open for the test
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=6)
    spec_counts = [
        h.get_telemetry(t).get("speculative_accepted")
        for t in range(h.n_populations)
    ]
    assert any(c is not None and c > 0 for c in spec_counts), spec_counts
    mu_true, sd_true = exact_posterior()
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(mu_true, abs=0.2)


def test_local_transition_speculation_matches_serial():
    abc_p = _local_transition(seed=17, pipeline=True)
    abc_p.speculation_min_adapt_s = 0.0  # force the auto-gate open
    abc_p.new("sqlite://", {"x": X_OBS})
    h_p = abc_p.run(max_nr_populations=5)
    spec_counts = [
        h_p.get_telemetry(t).get("speculative_accepted")
        for t in range(h_p.n_populations)
    ]
    assert any(c is not None and c > 0 for c in spec_counts), spec_counts

    abc_s = _local_transition(seed=17, pipeline=False)
    abc_s.new("sqlite://", {"x": X_OBS})
    h_s = abc_s.run(max_nr_populations=5)

    mu_true, _ = exact_posterior()
    for h in (h_p, h_s):
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(mu_true, abs=0.2)
    # epsilons follow the same trajectory statistically
    eps_p = h_p.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    eps_s = h_s.get_all_populations().query("t >= 1")["epsilon"].to_numpy()
    assert len(eps_p) == len(eps_s)
    np.testing.assert_allclose(eps_p, eps_s, rtol=0.5)


def test_adaptive_distance_never_speculates():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    abc = pt.ABCSMC(model, prior, pt.AdaptivePNormDistance(p=2),
                    population_size=100, eps=pt.MedianEpsilon(),
                    transitions=pt.LocalTransition(), seed=1)
    assert not abc._speculation_capable()
