"""Stochastic (noisy) ABC integration tests — config 4.

Mirrors the reference's stochastic-acceptor tests: StochasticAcceptor +
kernel distance + Temperature must recover the exact-likelihood posterior
(SURVEY.md §4 'stochastic-acceptor vs exact likelihood').
"""
import jax
import numpy as np
import pytest
import scipy.stats as st

import pyabc_tpu as pt

NOISE_SD = 0.7
PRIOR_SD = 1.0
X_OBS = 0.8


def _deterministic_model():
    """Simulator with NO sampling noise: y(theta) = theta; noise lives in
    the kernel (the canonical noisy-ABC formulation)."""

    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    return model


def exact_posterior():
    var = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
    return var * X_OBS / NOISE_SD**2, np.sqrt(var)


class TestStochasticAcceptorDevicePath:
    def test_recovers_exact_posterior(self):
        model = _deterministic_model()
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        kernel = pt.IndependentNormalKernel(var=[NOISE_SD**2])
        abc = pt.ABCSMC(
            model, prior, kernel,
            population_size=500,
            eps=pt.Temperature(),
            acceptor=pt.StochasticAcceptor(),
            seed=21,
        )
        abc.new("sqlite://", {"x": X_OBS})
        # default minimum_epsilon stops at T = 1 (exact posterior), the
        # reference convention for temperature schedules
        h = abc.run(max_nr_populations=8)
        mu_true, sd_true = exact_posterior()
        df, w = h.get_distribution(0)
        mu = float(np.sum(df["theta"] * w))
        sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
        assert mu == pytest.approx(mu_true, abs=0.15)
        assert sd == pytest.approx(sd_true, abs=0.15)
        # temperature must have decayed to exactly 1 in the final generation
        assert abc.eps(h.max_t) == pytest.approx(1.0)

    def test_requires_temperature_pairing(self):
        model = _deterministic_model()
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        with pytest.raises(ValueError, match="Temperature"):
            pt.ABCSMC(model, prior, pt.IndependentNormalKernel(var=[1.0]),
                      acceptor=pt.StochasticAcceptor(), eps=pt.MedianEpsilon())
        with pytest.raises(ValueError, match="StochasticKernel"):
            pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                      acceptor=pt.StochasticAcceptor(), eps=pt.Temperature())


class TestTemperatureSchemes:
    def _ctx(self, temps=None):
        import pandas as pd

        vals = -np.abs(np.random.default_rng(0).normal(0, 5, 300))
        return {
            "get_weighted_distances": lambda: pd.DataFrame(
                {"distance": vals, "w": np.full(300, 1 / 300)}
            ),
            "pdf_norm": 0.0,
            "kernel_scale": "SCALE_LOG",
        }

    def test_acceptance_rate_scheme_hits_target(self):
        scheme = pt.AcceptanceRateScheme(target_rate=0.3)
        ctx = self._ctx()
        T = scheme(1, prev_temperature=1e4, **ctx)
        df = ctx["get_weighted_distances"]()
        rate = np.mean(np.minimum(1.0, np.exp(df["distance"] / T)))
        assert rate == pytest.approx(0.3, abs=0.05)

    def test_exp_decay_fixed_iter_lands_at_one(self):
        scheme = pt.ExpDecayFixedIterScheme()
        T = 256.0
        temps = []
        for t in range(1, 9):
            T = scheme(t, prev_temperature=T, max_nr_populations=9)
            temps.append(T)
        assert temps[-1] == pytest.approx(1.0)
        assert all(np.diff(temps) < 0)

    def test_exp_decay_fixed_ratio(self):
        scheme = pt.ExpDecayFixedRatioScheme(alpha=0.5)
        assert scheme(1, prev_temperature=8.0) == pytest.approx(4.0)

    def test_ess_scheme_monotone(self):
        scheme = pt.EssScheme(target_relative_ess=0.8)
        ctx = self._ctx()
        T = scheme(1, prev_temperature=None, **ctx)
        assert T >= 1.0

    def test_temperature_enforces_decay_and_final_one(self):
        temp = pt.Temperature()
        import pandas as pd

        df = pd.DataFrame({"distance": -np.abs(
            np.random.default_rng(1).normal(0, 3, 200)),
            "w": np.full(200, 1 / 200)})
        temp.initialize(0, get_weighted_distances=lambda: df,
                        max_nr_populations=4,
                        acceptor_config={"pdf_norm": 0.0,
                                         "kernel_scale": "SCALE_LOG"})
        t0 = temp(0)
        temp.update(1, get_weighted_distances=lambda: df,
                    acceptance_rate=0.3,
                    acceptor_config={"pdf_norm": 0.0,
                                     "kernel_scale": "SCALE_LOG"})
        assert temp(1) <= t0
        temp.update(3, get_weighted_distances=lambda: df,
                    acceptance_rate=0.3,
                    acceptor_config={"pdf_norm": 0.0,
                                     "kernel_scale": "SCALE_LOG"})
        assert temp(3) == 1.0


class TestPdfNorm:
    def test_max_found(self):
        assert pt.pdf_norm_max_found(pdf_max=None, max_found=-2.0,
                                     prev_pdf_norm=-5.0) == -2.0
        assert pt.pdf_norm_max_found(pdf_max=-1.0, max_found=-2.0,
                                     prev_pdf_norm=None) == -1.0

    def test_scaled(self):
        norm = pt.ScaledPDFNorm(factor=10)
        vals = np.linspace(-50, -10, 100)
        out = norm(kernel_val=vals, pdf_max=None, max_found=-10.0,
                   prev_pdf_norm=None)
        assert out <= -10.0 + 1e-9


def test_list_temperature_ladder_is_respected():
    """ListTemperature (reference parity): user-pinned temperature ladder,
    no adaptation; the run's temperature trajectory IS the list."""
    import jax

    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    ladder = [16.0, 8.0, 2.0, 1.0]
    abc = pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.IndependentNormalKernel(var=[0.09]),
        population_size=200,
        eps=pt.ListTemperature(ladder),
        acceptor=pt.StochasticAcceptor(), seed=4,
    )
    abc.new("sqlite://", {"x": 0.5})
    h = abc.run(max_nr_populations=4)
    eps_used = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    np.testing.assert_allclose(eps_used, ladder[: len(eps_used)])
    assert h.n_populations == 4
