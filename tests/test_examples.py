"""Execute every example script (reference parity: doc/examples notebooks
run as CI integration smoke tests). Shrunk via the EX_POP / EX_GENS env
knobs each example honors; each example asserts its own statistical sanity.
"""
import os
import runpy

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.join(os.path.dirname(HERE), "examples")


@pytest.mark.parametrize("script", sorted(
    f for f in os.listdir(EXAMPLES) if f.endswith(".py")
))
def test_example_runs(script, monkeypatch):
    monkeypatch.setenv("EX_POP", "150")
    monkeypatch.setenv("EX_GENS", "3")
    mod = runpy.run_path(os.path.join(EXAMPLES, script), run_name="example")
    history = mod["main"]()
    assert history.n_populations >= 1
