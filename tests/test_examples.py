"""Execute every example script (reference parity: doc/examples notebooks
run as CI integration smoke tests). Shrunk via the EX_POP / EX_GENS env
knobs each example honors; each example asserts its own statistical sanity.
"""
import os
import runpy

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.join(os.path.dirname(HERE), "examples")


#: examples whose shrunk smoke runs still spawn subprocess farms or big
#: compiles — full-lane only (tier-1 runs the rest)
SLOW_EXAMPLES = {
    "05_external_model.py", "07_elastic_workers.py",
    "08_temperature_schemes.py",
}


@pytest.mark.parametrize("script", [
    pytest.param(f, marks=pytest.mark.slow) if f in SLOW_EXAMPLES else f
    for f in sorted(f for f in os.listdir(EXAMPLES) if f.endswith(".py"))
])
def test_example_runs(script, monkeypatch):
    monkeypatch.setenv("EX_POP", "150")
    monkeypatch.setenv("EX_GENS", "3")
    mod = runpy.run_path(os.path.join(EXAMPLES, script), run_name="example")
    history = mod["main"]()
    assert history.n_populations >= 1
