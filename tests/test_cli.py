"""CLI entry-point tests (abc-export; reference `pyabc/storage/export.py`)."""
import numpy as np
from click.testing import CliRunner

import pyabc_tpu as pt
from pyabc_tpu.cli import export_cmd


def _make_db(tmp_path):
    db = f"{tmp_path}/cli.db"

    def model(par):
        return {"y": par["mu"] + 0.3 * np.random.normal()}

    np.random.seed(0)
    abc = pt.ABCSMC(
        pt.SimpleModel(model),
        pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0)),
        pt.PNormDistance(p=2), population_size=30,
        eps=pt.QuantileEpsilon(initial_epsilon=2.0, alpha=0.5),
        sampler=pt.SingleCoreSampler(),
    )
    abc.new(f"sqlite:///{db}", {"y": 0.5})
    abc.run(max_nr_populations=2)
    return db


def test_export_populations_csv(tmp_path):
    db = _make_db(tmp_path)
    res = CliRunner().invoke(export_cmd, [db, "--what", "populations"])
    assert res.exit_code == 0, res.output
    lines = res.output.strip().splitlines()
    assert lines[0].startswith("t,")
    assert len(lines) >= 3  # PRE_TIME + 2 generations


def test_export_particles_to_file(tmp_path):
    db = _make_db(tmp_path)
    out = f"{tmp_path}/particles.csv"
    res = CliRunner().invoke(export_cmd, [db, "--out", out])
    assert res.exit_code == 0, res.output
    import pandas as pd

    df = pd.read_csv(out)
    assert {"mu", "w"} <= set(df.columns)
    assert len(df) == 30
    assert np.isclose(df["w"].sum(), 1.0)


def test_export_model_probabilities(tmp_path):
    db = _make_db(tmp_path)
    res = CliRunner().invoke(
        export_cmd, [db, "--what", "model-probabilities", "--format", "json"]
    )
    assert res.exit_code == 0, res.output
    import json

    rows = json.loads(res.output)
    # one row per generation; single model => probability 1.0
    assert len(rows) == 2
    assert all(np.isclose(sum(r.values()), 1.0) for r in rows)


def test_bench_defaults_single_source():
    """bench.py and abc-bench resolve defaults from ONE module (round-2
    advisor: the CLI had re-hardcoded the generation count by hand)."""
    import ast
    import os

    from pyabc_tpu.utils import bench_defaults as bd

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for fname in ("bench.py", os.path.join("pyabc_tpu", "cli.py")):
        src = open(os.path.join(here, fname)).read()
        tree = ast.parse(src)
        # no stray numeric fallback next to the bench env knobs: every
        # os.environ.get("PYABC_TPU_BENCH_*", <default>) must take its
        # default from bench_defaults, not a literal
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and str(node.args[0].value).startswith("PYABC_TPU_BENCH_")
                    and len(node.args) > 1):
                assert not isinstance(node.args[1], ast.Constant), (
                    f"{fname}: literal default for {node.args[0].value}; "
                    "use pyabc_tpu.utils.bench_defaults"
                )
    # the G-alignment invariant the sizing comment promises: gen 0
    # rides the first chunk (round 5), so a run is (GENS + 2)/G chunks
    assert (bd.DEFAULT_GENS + 2) % bd.DEFAULT_G == 0
