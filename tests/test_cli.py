"""CLI entry-point tests (abc-export; reference `pyabc/storage/export.py`)."""
import numpy as np
from click.testing import CliRunner

import pyabc_tpu as pt
from pyabc_tpu.cli import export_cmd


def _make_db(tmp_path):
    db = f"{tmp_path}/cli.db"

    def model(par):
        return {"y": par["mu"] + 0.3 * np.random.normal()}

    np.random.seed(0)
    abc = pt.ABCSMC(
        pt.SimpleModel(model),
        pt.Distribution(mu=pt.RV("uniform", -2.0, 4.0)),
        pt.PNormDistance(p=2), population_size=30,
        eps=pt.QuantileEpsilon(initial_epsilon=2.0, alpha=0.5),
        sampler=pt.SingleCoreSampler(),
    )
    abc.new(f"sqlite:///{db}", {"y": 0.5})
    abc.run(max_nr_populations=2)
    return db


def test_export_populations_csv(tmp_path):
    db = _make_db(tmp_path)
    res = CliRunner().invoke(export_cmd, [db, "--what", "populations"])
    assert res.exit_code == 0, res.output
    lines = res.output.strip().splitlines()
    assert lines[0].startswith("t,")
    assert len(lines) >= 3  # PRE_TIME + 2 generations


def test_export_particles_to_file(tmp_path):
    db = _make_db(tmp_path)
    out = f"{tmp_path}/particles.csv"
    res = CliRunner().invoke(export_cmd, [db, "--out", out])
    assert res.exit_code == 0, res.output
    import pandas as pd

    df = pd.read_csv(out)
    assert {"mu", "w"} <= set(df.columns)
    assert len(df) == 30
    assert np.isclose(df["w"].sum(), 1.0)


def test_export_model_probabilities(tmp_path):
    db = _make_db(tmp_path)
    res = CliRunner().invoke(
        export_cmd, [db, "--what", "model-probabilities", "--format", "json"]
    )
    assert res.exit_code == 0, res.output
    import json

    rows = json.loads(res.output)
    # one row per generation; single model => probability 1.0
    assert len(rows) == 2
    assert all(np.isclose(sum(r.values()), 1.0) for r in rows)
