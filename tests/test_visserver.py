"""Web dashboard (visserver) tests: serve a real History, fetch every route.

Mirrors the reference's test style for the Flask visserver: generate a tiny
History, stand up the real server on an ephemeral port, assert routes
respond with the right content types (multi-node analog: real local
infrastructure, no mocks — SURVEY.md §4).
"""
import json
import urllib.request

import jax
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.visserver import serve

PNG_MAGIC = b"\x89PNG\r\n\x1a\n"


@pytest.fixture(scope="module")
def served_history(tmp_path_factory):
    db_path = tmp_path_factory.mktemp("visserver") / "dash.db"
    db = f"sqlite:///{db_path}"

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=80,
                    eps=pt.ListEpsilon([1.5, 0.8, 0.5]), seed=17)
    abc.new(db, {"x": 1.0})
    h = abc.run(max_nr_populations=3)
    httpd = serve(db, port=0, block=False)
    base = f"http://127.0.0.1:{httpd.server_port}"
    yield base, h
    httpd.shutdown()
    httpd.server_close()


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.headers.get("Content-Type"), r.read()


def test_index_lists_runs(served_history):
    base, h = served_history
    status, ctype, body = _get(base + "/")
    assert status == 200 and ctype.startswith("text/html")
    assert f"/abc/{h.id}" in body.decode()


def test_run_page(served_history):
    base, h = served_history
    status, ctype, body = _get(f"{base}/abc/{h.id}")
    text = body.decode()
    assert status == 200
    assert "Populations" in text and "theta" in text
    assert "epsilons.png" in text


@pytest.mark.parametrize("plot", [
    "epsilons", "eps_walltime", "sample_numbers", "acceptance_rates",
    "effective_sample_sizes", "walltime", "model_probabilities",
])
def test_diagnostic_plots(served_history, plot):
    base, h = served_history
    status, ctype, body = _get(f"{base}/abc/{h.id}/plot/{plot}.png")
    assert status == 200 and ctype == "image/png"
    assert body.startswith(PNG_MAGIC)


def test_kde_routes(served_history):
    base, h = served_history
    status, ctype, body = _get(f"{base}/abc/{h.id}/kde/0/theta.png")
    assert status == 200 and body.startswith(PNG_MAGIC)
    status, ctype, body = _get(f"{base}/abc/{h.id}/kde/0/theta.png?t=1")
    assert status == 200 and body.startswith(PNG_MAGIC)
    status, ctype, body = _get(f"{base}/abc/{h.id}/kde_matrix/0.png")
    assert status == 200 and body.startswith(PNG_MAGIC)


def test_populations_api(served_history):
    base, h = served_history
    status, ctype, body = _get(f"{base}/api/{h.id}/populations")
    assert status == 200 and ctype == "application/json"
    rows = json.loads(body)
    ts = [r["t"] for r in rows if r["t"] >= 0]
    assert ts == [0, 1, 2]
    eps = [r["epsilon"] for r in rows if r["t"] >= 0]
    np.testing.assert_allclose(eps, [1.5, 0.8, 0.5])


def test_unknown_routes(served_history):
    base, h = served_history
    status, _, _ = _get_status(base + "/nope")
    assert status == 404
    status, _, _ = _get_status(f"{base}/abc/{h.id}/plot/bogus.png")
    assert status == 500


def _get_status(url):
    import urllib.error

    try:
        return _get(url)
    except urllib.error.HTTPError as e:
        return e.code, None, None
