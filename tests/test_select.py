"""Threshold neighbor selection (ops/select.py) + incremental refit.

Parity contract (ISSUE 3): EXACT agreement with ``lax.top_k`` below the
fallback cutoff (the auto rule keeps the sort there), documented
tolerance above it — ties at the radius, bisection resolution and the
candidate stride are the three deviation sources, each bounded.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

import pyabc_tpu as pt
from pyabc_tpu.ops import select as S


def _sq(arr):
    d = arr[:, None, :] - arr[None, :, :]
    return (d * d).sum(-1)


def test_radius_bisect_reproduces_kth_distance():
    """The bisected radius must sit exactly at the kth-smallest distance
    (up to f32 bisection resolution): count(sq <= r) == k without ties."""
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(160, 3)).astype(np.float32)
    sq = jnp.asarray(_sq(arr))
    k = 40
    r = S.radius_bisect(sq, jnp.asarray(k))
    cnt = np.asarray((np.asarray(sq) <= np.asarray(r)[:, None]).sum(1))
    np.testing.assert_array_equal(cnt, k)
    # the selected set IS the exact k nearest (continuous data: no ties)
    idx, cnt2 = S.compact_within_radius(sq, r, k)
    exact = np.argsort(np.asarray(sq), axis=1)[:, :k]
    for i in range(arr.shape[0]):
        assert set(np.asarray(idx[i])[: int(cnt2[i])]) == set(exact[i])


def test_compact_within_radius_order_and_clip():
    sq = jnp.asarray([[0.0, 5.0, 1.0, 3.0, 9.0]], jnp.float32)
    idx, cnt = S.compact_within_radius(sq, jnp.asarray([3.5]), k_cap=2)
    # within radius: candidates 0, 2, 3 — clipped to capacity 2, in
    # candidate order (the documented capacity deviation)
    assert int(cnt[0]) == 2
    np.testing.assert_array_equal(np.asarray(idx[0]), [0, 2])


def test_threshold_neighbors_strided_subsample():
    """stride > 1: indices live on the stride grid, count targets
    ceil(k / stride), and the set is the within-radius subsample."""
    rng = np.random.default_rng(1)
    arr = rng.normal(size=(128, 2)).astype(np.float32)
    sq = jnp.asarray(_sq(arr))
    idx, cnt, r = S.threshold_neighbors(sq, jnp.asarray(32), 32, stride=4)
    idx = np.asarray(idx)
    cnt = np.asarray(cnt)
    assert (idx % 4 == 0).all()
    # ~k/stride selected per row, never more than the strided buffer
    assert (cnt >= 1).all() and (cnt <= 8).all()
    sqn = np.asarray(sq)
    rn = np.asarray(r)
    for i in range(0, 128, 17):
        sel = set(idx[i][: cnt[i]])
        within = {j for j in range(0, 128, 4) if sqn[i, j] <= rn[i]}
        assert sel == within


def test_device_fit_auto_is_exact_below_cutoff():
    """selection='auto' below the cutoff must be the top_k path:
    bit-identical to selection='topk'."""
    rng = np.random.default_rng(2)
    arr = rng.normal(size=(100, 2)).astype(np.float32)
    w = np.full(100, 0.01, np.float32)
    kw = dict(dim=2, scaling=1.0, k=25)
    auto = pt.LocalTransition.device_fit(jnp.asarray(arr), jnp.asarray(w),
                                         **kw)
    topk = pt.LocalTransition.device_fit(jnp.asarray(arr), jnp.asarray(w),
                                         selection="topk", **kw)
    for key in ("chols", "precs", "logdets"):
        np.testing.assert_array_equal(np.asarray(auto[key]),
                                      np.asarray(topk[key]))


def test_threshold_matches_topk_and_host():
    """Unstrided threshold selection: same neighbor sets as top_k on
    continuous data, so the covariances agree to f32 — and both match
    the host f64 fit (the documented-tolerance regime is the stride,
    tested separately)."""
    rng = np.random.default_rng(3)
    n, dim = 256, 3
    arr = np.column_stack([
        rng.normal(0, 1, n), rng.normal(2, 0.5, n), rng.normal(-1, 2, n)
    ]).astype(np.float32)
    w = np.full(n, 1.0 / n, np.float32)
    host = pt.LocalTransition(k_fraction=0.25)
    host.fit(pd.DataFrame(arr, columns=list("abc")), w.astype(np.float64))
    k = host._effective_k(n, dim)
    thr = pt.LocalTransition.device_fit(
        jnp.asarray(arr), jnp.asarray(w), dim=dim, scaling=1.0, k=k,
        selection="threshold", bisect_stride=1,
    )
    topk = pt.LocalTransition.device_fit(
        jnp.asarray(arr), jnp.asarray(w), dim=dim, scaling=1.0, k=k,
        selection="topk",
    )
    np.testing.assert_allclose(np.asarray(thr["chols"]),
                               np.asarray(topk["chols"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(thr["logdets"]), host._logdets,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(thr["chols"]), host._chols,
                               rtol=5e-3, atol=5e-3)


def test_threshold_strided_documented_tolerance():
    """stride 4: the covariance is a ~k/4-point subsample estimate of
    the same neighborhood — bandwidths must agree with the exact fit to
    the documented few-percent tolerance, not exactly."""
    rng = np.random.default_rng(4)
    n, dim = 512, 2
    arr = rng.normal(size=(n, dim)).astype(np.float32)
    w = np.full(n, 1.0 / n, np.float32)
    k = 128
    topk = pt.LocalTransition.device_fit(
        jnp.asarray(arr), jnp.asarray(w), dim=dim, scaling=1.0, k=k,
        selection="topk",
    )
    thr = pt.LocalTransition.device_fit(
        jnp.asarray(arr), jnp.asarray(w), dim=dim, scaling=1.0, k=k,
        selection="threshold", bisect_stride=4,
    )
    ld_t = np.asarray(topk["logdets"])
    ld_s = np.asarray(thr["logdets"])
    # logdet of a d-dim covariance: 25% relative error in cov entries is
    # ~0.5 in logdet at d=2; subsample noise at k/4=32 points is ~18%
    assert np.median(np.abs(ld_s - ld_t)) < 0.35
    assert np.abs(ld_s - ld_t).max() < 1.5


def test_apply_rowwise_blocked_runs_only_changed_rows():
    n = 37
    x = jnp.asarray(np.arange(n, dtype=np.float32))
    changed = jnp.asarray(np.arange(n) % 3 == 0)
    prev = (jnp.full((n,), -1.0), jnp.full((n,), -2.0))

    def fn(xb):
        return xb * 10.0, xb * 100.0

    (a, b), n_changed = S.apply_rowwise_blocked(
        fn, changed, prev, x, block=8
    )
    assert int(n_changed) == int(np.sum(np.arange(n) % 3 == 0))
    a, b = np.asarray(a), np.asarray(b)
    ch = np.arange(n) % 3 == 0
    np.testing.assert_allclose(a[ch], np.arange(n)[ch] * 10.0)
    np.testing.assert_allclose(b[ch], np.arange(n)[ch] * 100.0)
    np.testing.assert_allclose(a[~ch], -1.0)
    np.testing.assert_allclose(b[~ch], -2.0)


def test_apply_rowwise_blocked_none_changed():
    n = 16
    x = jnp.asarray(np.ones(n, np.float32))
    prev = (jnp.full((n,), 7.0),)
    (out,), n_changed = S.apply_rowwise_blocked(
        lambda xb: (xb * 0.0,), jnp.zeros((n,), bool), prev, x, block=4
    )
    assert int(n_changed) == 0
    np.testing.assert_allclose(np.asarray(out), 7.0)


def test_device_fit_update_reuses_unchanged_rows():
    """Incremental refit: identical population -> zero rows factorized,
    params identical; fresh population -> everything changes and the
    result matches the plain fit exactly."""
    rng = np.random.default_rng(5)
    n, dim = 200, 2
    arr = rng.normal(size=(n, dim)).astype(np.float32)
    w = jnp.full((n,), 1.0 / n, jnp.float32)
    X = jnp.asarray(arr)
    kw = dict(dim=dim, scaling=1.0, k=50)
    base = pt.LocalTransition.device_fit(X, w, **kw)
    same, nch = pt.LocalTransition.device_fit_update(X, w, base, **kw)
    assert int(nch) == 0
    for key in ("chols", "precs", "logdets"):
        np.testing.assert_array_equal(np.asarray(same[key]),
                                      np.asarray(base[key]))
    X2 = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    upd, nch2 = pt.LocalTransition.device_fit_update(X2, w, base, **kw)
    plain = pt.LocalTransition.device_fit(X2, w, **kw)
    assert int(nch2) > n * 0.9
    for key in ("chols", "precs", "logdets"):
        np.testing.assert_array_equal(np.asarray(upd[key]),
                                      np.asarray(plain[key]))


def test_device_fit_update_local_perturbation_partial():
    """Moving ONE particle far from the bulk changes only the rows whose
    neighborhood it participates in (its own row + former/new neighbors)
    — the changed-row count must stay well below n."""
    rng = np.random.default_rng(6)
    n, dim = 300, 2
    arr = rng.normal(size=(n, dim)).astype(np.float32)
    # an outlier cluster far away: its rows' neighborhoods are local
    arr[250:] += 100.0
    w = jnp.full((n,), 1.0 / n, jnp.float32)
    kw = dict(dim=dim, scaling=1.0, k=20)
    base = pt.LocalTransition.device_fit(jnp.asarray(arr), w, **kw)
    arr2 = arr.copy()
    arr2[260] += 1.0  # nudge one outlier-cluster member
    upd, nch = pt.LocalTransition.device_fit_update(
        jnp.asarray(arr2), w, base, **kw)
    plain = pt.LocalTransition.device_fit(jnp.asarray(arr2), w, **kw)
    # only the outlier cluster's neighborhoods can change (k=20 < 50)
    assert 0 < int(nch) <= 60, int(nch)
    for key in ("chols", "precs", "logdets"):
        np.testing.assert_array_equal(np.asarray(upd[key]),
                                      np.asarray(plain[key]))


def test_k_max_deviation_host_device_parity():
    """k_max caps the effective neighbor count identically on host and
    device (the documented k-cap deviation from k_fraction * n)."""
    tr = pt.LocalTransition(k_fraction=0.5, k_max=30)
    assert tr._effective_k(200, 2) == 30
    assert tr._effective_k(40, 2) == 20  # rule below the cap: untouched
    rng = np.random.default_rng(7)
    arr = rng.normal(size=(200, 2)).astype(np.float32)
    w = jnp.full((200,), 1.0 / 200, jnp.float32)
    capped = pt.LocalTransition.device_fit(
        jnp.asarray(arr), w, dim=2, scaling=1.0, k_cap=30,
        k_fraction=0.5, k_max=30,
    )
    explicit = pt.LocalTransition.device_fit(
        jnp.asarray(arr), w, dim=2, scaling=1.0, k=30,
    )
    np.testing.assert_array_equal(np.asarray(capped["chols"]),
                                  np.asarray(explicit["chols"]))


def test_local_transition_rejects_bad_selection():
    with pytest.raises(ValueError):
        pt.LocalTransition(selection="radix")
