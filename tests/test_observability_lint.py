"""Repo lint: telemetry stays in the observability subsystem.

Two rules, enforced on source text at collection time:

1. Instrumented modules must not call ``time.time()`` (or
   ``time.perf_counter()``) directly — all host timing goes through the
   injected clock (``pyabc_tpu.observability.clock``), so spans and
   deadlines are immune to wall-clock steps and tests can drive a
   VirtualClock. Round 8 hardened this for the newly instrumented
   elastic path: the broker trio (broker/worker/sampler + the wire
   protocol) is PINNED in the list below — worker-side spans and the
   NTP-style offset samples are only mergeable because every timestamp
   on both sides of the wire comes from an injected clock.
2. No new ``phase_timings``-style ad-hoc telemetry containers outside
   ``pyabc_tpu/observability/`` — named span/metric instruments replace
   scatter-shot timing dicts, so every measurement has one schema, one
   clock, and one exporter.
"""
import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: modules wired into the observability subsystem; the clock rule holds
#: for each of them (extend this list when instrumenting a new module).
#: Entries ending in "/" pin every .py file under that directory.
INSTRUMENTED = [
    "bench.py",
    "pyabc_tpu/inference/smc.py",
    "pyabc_tpu/sampler/batched.py",
    "pyabc_tpu/broker/broker.py",
    "pyabc_tpu/broker/protocol.py",
    "pyabc_tpu/broker/sampler.py",
    "pyabc_tpu/broker/worker.py",
    "pyabc_tpu/storage/history.py",
    "pyabc_tpu/cli.py",
    "pyabc_tpu/resilience/",
    # round 10: the health-guard pair — the device word and its host
    # supervisor share the run's injected clock (detection->redispatch
    # recovery spans merge onto the run timeline)
    "pyabc_tpu/ops/health.py",
]

#: the distributed-tracing path: dropping any of these from INSTRUMENTED
#: would let raw-clock regressions silently corrupt the worker-span
#: merge (offsets are estimated between INJECTED clocks only)
TRACING_CRITICAL = {
    "pyabc_tpu/broker/broker.py",
    "pyabc_tpu/broker/protocol.py",
    "pyabc_tpu/broker/sampler.py",
    "pyabc_tpu/broker/worker.py",
}

#: the resilience subsystem (round 9) is pinned as a DIRECTORY: every
#: lease deadline, retry backoff, fault schedule and checkpoint
#: timestamp must live on the injected clock, or fault plans stop being
#: deterministic and recovery spans stop merging onto the run timeline
RESILIENCE_PIN = "pyabc_tpu/resilience/"


def _instrumented_files():
    for rel in INSTRUMENTED:
        if rel.endswith("/"):
            root = REPO / rel
            assert root.is_dir(), f"instrumented directory moved: {rel}"
            for path in sorted(root.rglob("*.py")):
                yield str(path.relative_to(REPO)), path
        else:
            yield rel, REPO / rel

_TIME_TIME = re.compile(r"\btime\.(?:time|perf_counter)\(")
_AD_HOC = re.compile(
    r"\b(?:phase|stage|step)_timings?\b|\bspan_math\b|\btelemetry_clock\b"
)


def _code_lines(path: Path):
    """(lineno, line) pairs with comments stripped (string-literal
    timing text, e.g. generated subprocess code, still counts — that
    code RUNS)."""
    for i, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.split("#", 1)[0]
        if line.strip():
            yield i, line


def test_instrumented_modules_use_injected_clock():
    offenders = []
    for rel, path in _instrumented_files():
        assert path.exists(), f"instrumented module moved: {rel}"
        for lineno, line in _code_lines(path):
            if _TIME_TIME.search(line):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "direct time.time()/time.perf_counter() calls in instrumented "
        "modules (use the observability clock — pyabc_tpu.observability."
        "SYSTEM_CLOCK or the tracer's injected clock):\n"
        + "\n".join(offenders)
    )


def test_tracing_critical_modules_stay_pinned():
    """The elastic-path tracing modules cannot be dropped from the
    enforced list: worker spans are merged onto the orchestrator
    timeline via clock offsets estimated between INJECTED clocks, so a
    single raw time.time() on either side of the wire would skew every
    merged span."""
    missing = TRACING_CRITICAL - set(INSTRUMENTED)
    assert not missing, (
        f"tracing-critical modules missing from INSTRUMENTED: {missing}"
    )


def test_resilience_package_stays_pinned():
    """The resilience package cannot be dropped from the enforced list:
    fault plans replay deterministically and lease/retry deadlines merge
    onto the run timeline only because every timestamp in the subsystem
    comes from an injected clock."""
    assert RESILIENCE_PIN in INSTRUMENTED, (
        f"{RESILIENCE_PIN} missing from INSTRUMENTED"
    )
    # and the directory expansion actually finds its modules
    pinned = [rel for rel, _p in _instrumented_files()
              if rel.startswith("pyabc_tpu/resilience/")]
    assert {"pyabc_tpu/resilience/faults.py",
            "pyabc_tpu/resilience/retry.py",
            "pyabc_tpu/resilience/lease.py",
            "pyabc_tpu/resilience/checkpoint.py",
            "pyabc_tpu/resilience/health.py"} <= set(pinned), pinned


def test_health_modules_stay_pinned():
    """The round-10 health pair cannot be dropped: the RunSupervisor's
    recovery spans and the fault plan's corruption schedule are only
    deterministic/mergeable on the injected clock (resilience/health.py
    rides the directory pin; ops/health.py is pinned explicitly)."""
    assert "pyabc_tpu/ops/health.py" in INSTRUMENTED
    pinned = {rel for rel, _p in _instrumented_files()}
    assert "pyabc_tpu/resilience/health.py" in pinned


#: a broad handler whose entire body is `pass`: `except:`,
#: `except Exception:`, `except BaseException:` (with or without `as e`)
_BARE_EXCEPT = re.compile(
    r"^\s*except\s*(?:\(?\s*(?:Exception|BaseException)\s*\)?"
    r"(?:\s+as\s+\w+)?)?\s*:\s*$"
)


def test_no_swallowed_broad_exceptions():
    """Repo-wide lint (round 10): no `except Exception: pass` (or bare
    `except:` / `except BaseException:` with a pass-only body) anywhere
    in pyabc_tpu/. Silently swallowed errors are exactly the failure
    mode the health-guard PR exists to eliminate — a broad handler must
    log, count, re-raise, or otherwise leave a trace. Narrow handlers
    (`except FileNotFoundError: pass`) stay legal: suppressing a SPECIFIC
    expected condition is a statement, suppressing everything is a hole."""
    offenders = []
    for path in sorted((REPO / "pyabc_tpu").rglob("*.py")):
        lines = list(_code_lines(path))
        rel = path.relative_to(REPO)
        for i, (lineno, line) in enumerate(lines):
            if not _BARE_EXCEPT.match(line):
                continue
            if i + 1 < len(lines) and lines[i + 1][1].strip() == "pass":
                offenders.append(f"{rel}:{lineno}: {line.strip()} pass")
    assert not offenders, (
        "broad exception handlers with a pass-only body (log/count/"
        "re-raise instead — swallowed errors are invisible failures):\n"
        + "\n".join(offenders)
    )


def test_no_ad_hoc_telemetry_outside_observability():
    offenders = []
    for path in sorted((REPO / "pyabc_tpu").rglob("*.py")):
        if "observability" in path.parts:
            continue
        rel = path.relative_to(REPO)
        for lineno, line in _code_lines(path):
            if _AD_HOC.search(line):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    for rel in ("bench.py", "profile_gen.py"):
        for lineno, line in _code_lines(REPO / rel):
            if _AD_HOC.search(line):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "ad-hoc telemetry containers outside pyabc_tpu/observability/ "
        "(add a named span or metric instrument instead):\n"
        + "\n".join(offenders)
    )
