"""Repo lint: telemetry stays in the observability subsystem.

Round 11 moved the enforcement onto the ``abc-lint`` AST engine
(``pyabc_tpu/analysis/`` — see ``tests/test_static_analysis.py`` for the
engine's own suite and the repo-wide zero-unbaselined gate). This file
keeps two things:

1. thin wrappers running the engine's CLOCK001 / TELEM001 / EXC001 rules
   over the historically pinned surfaces, so the original guarantees
   keep their own named tests (and their failure messages);
2. the pin tests VERBATIM: ``INSTRUMENTED`` is no longer what *limits*
   enforcement (the rules are repo-wide now), but dropping a
   tracing-critical module, the resilience directory, or the health pair
   from the pinned list must still fail loudly — the list documents
   which modules' clocks the span-merge correctness depends on.
"""
from pathlib import Path

from pyabc_tpu.analysis import run_analysis
from pyabc_tpu.analysis.rules.clock import Clock001
from pyabc_tpu.analysis.rules.exceptions import Exc001
from pyabc_tpu.analysis.rules.telemetry import Telem001

REPO = Path(__file__).resolve().parent.parent

#: modules wired into the observability subsystem; the clock rule holds
#: for each of them (extend this list when instrumenting a new module).
#: Entries ending in "/" pin every .py file under that directory.
INSTRUMENTED = [
    "bench.py",
    "pyabc_tpu/inference/smc.py",
    # round 12: the dispatch engine owns every chunk round trip — its
    # fetch/probe timestamps and spans must live on the injected clock
    "pyabc_tpu/inference/dispatch.py",
    "pyabc_tpu/sampler/batched.py",
    "pyabc_tpu/broker/broker.py",
    "pyabc_tpu/broker/protocol.py",
    "pyabc_tpu/broker/sampler.py",
    "pyabc_tpu/broker/worker.py",
    "pyabc_tpu/storage/history.py",
    "pyabc_tpu/cli.py",
    "pyabc_tpu/resilience/",
    # round 10: the health-guard pair — the device word and its host
    # supervisor share the run's injected clock (detection->redispatch
    # recovery spans merge onto the run timeline)
    "pyabc_tpu/ops/health.py",
]

#: the distributed-tracing path: dropping any of these from INSTRUMENTED
#: would let raw-clock regressions silently corrupt the worker-span
#: merge (offsets are estimated between INJECTED clocks only)
TRACING_CRITICAL = {
    "pyabc_tpu/broker/broker.py",
    "pyabc_tpu/broker/protocol.py",
    "pyabc_tpu/broker/sampler.py",
    "pyabc_tpu/broker/worker.py",
}

#: the resilience subsystem (round 9) is pinned as a DIRECTORY: every
#: lease deadline, retry backoff, fault schedule and checkpoint
#: timestamp must live on the injected clock, or fault plans stop being
#: deterministic and recovery spans stop merging onto the run timeline
RESILIENCE_PIN = "pyabc_tpu/resilience/"


def _instrumented_files():
    for rel in INSTRUMENTED:
        if rel.endswith("/"):
            root = REPO / rel
            assert root.is_dir(), f"instrumented directory moved: {rel}"
            for path in sorted(root.rglob("*.py")):
                yield str(path.relative_to(REPO)), path
        else:
            yield rel, REPO / rel


def _run(rule, paths):
    return run_analysis(REPO, paths, [rule])


def test_instrumented_modules_use_injected_clock():
    """Engine-backed (CLOCK001): the historically pinned modules carry
    ZERO raw clock reads — not even suppressed ones (suppressions are for
    the clock implementation itself, which is not on this list)."""
    paths = []
    for rel, path in _instrumented_files():
        assert path.exists(), f"instrumented module moved: {rel}"
        paths.append(path)
    res = _run(Clock001(), paths)
    offenders = [f"{f.path}:{f.line}: {f.code}"
                 for f in res.findings if f.rule == "CLOCK001"]
    assert not offenders, (
        "raw clock reads in instrumented modules (use the observability "
        "clock — pyabc_tpu.observability.SYSTEM_CLOCK or the tracer's "
        "injected clock):\n" + "\n".join(offenders)
    )


def test_clock_discipline_is_repo_wide():
    """Round 11: the allowlist inverted. CLOCK001 holds across ALL of
    pyabc_tpu/ + bench.py; the only legal raw reads are the SystemClock
    implementation's two, each suppressed with a reason."""
    from pyabc_tpu.analysis import iter_python_files
    files = iter_python_files([REPO / "pyabc_tpu", REPO / "bench.py"])
    res = _run(Clock001(), files)
    assert res.open == [], [f.to_dict() for f in res.open]
    assert {f.path for f in res.suppressed} <= {
        "pyabc_tpu/observability/clock.py"}


def test_tracing_critical_modules_stay_pinned():
    """The elastic-path tracing modules cannot be dropped from the
    enforced list: worker spans are merged onto the orchestrator
    timeline via clock offsets estimated between INJECTED clocks, so a
    single raw time.time() on either side of the wire would skew every
    merged span."""
    missing = TRACING_CRITICAL - set(INSTRUMENTED)
    assert not missing, (
        f"tracing-critical modules missing from INSTRUMENTED: {missing}"
    )


def test_resilience_package_stays_pinned():
    """The resilience package cannot be dropped from the enforced list:
    fault plans replay deterministically and lease/retry deadlines merge
    onto the run timeline only because every timestamp in the subsystem
    comes from an injected clock."""
    assert RESILIENCE_PIN in INSTRUMENTED, (
        f"{RESILIENCE_PIN} missing from INSTRUMENTED"
    )
    # and the directory expansion actually finds its modules
    pinned = [rel for rel, _p in _instrumented_files()
              if rel.startswith("pyabc_tpu/resilience/")]
    assert {"pyabc_tpu/resilience/faults.py",
            "pyabc_tpu/resilience/retry.py",
            "pyabc_tpu/resilience/lease.py",
            "pyabc_tpu/resilience/checkpoint.py",
            "pyabc_tpu/resilience/health.py"} <= set(pinned), pinned


def test_health_modules_stay_pinned():
    """The round-10 health pair cannot be dropped: the RunSupervisor's
    recovery spans and the fault plan's corruption schedule are only
    deterministic/mergeable on the injected clock (resilience/health.py
    rides the directory pin; ops/health.py is pinned explicitly)."""
    assert "pyabc_tpu/ops/health.py" in INSTRUMENTED
    pinned = {rel for rel, _p in _instrumented_files()}
    assert "pyabc_tpu/resilience/health.py" in pinned


def test_no_swallowed_broad_exceptions():
    """Engine-backed (EXC001, round 11): the AST form also catches the
    multi-line swallowing bodies the old regex missed (`continue`, bare
    `return`). Repo-wide over pyabc_tpu/ with zero open findings."""
    from pyabc_tpu.analysis import iter_python_files
    files = iter_python_files([REPO / "pyabc_tpu"])
    res = _run(Exc001(), files)
    offenders = [f"{f.path}:{f.line}: {f.code}" for f in res.open]
    assert not offenders, (
        "broad exception handlers with a pass-equivalent body (log/count/"
        "re-raise instead — swallowed errors are invisible failures):\n"
        + "\n".join(offenders)
    )


def test_no_ad_hoc_telemetry_outside_observability():
    """Engine-backed (TELEM001): named instruments only, repo-wide."""
    from pyabc_tpu.analysis import iter_python_files
    files = iter_python_files([REPO / "pyabc_tpu"])
    files += [REPO / "bench.py", REPO / "profile_gen.py"]
    res = _run(Telem001(), files)
    offenders = [f"{f.path}:{f.line}: {f.code}" for f in res.open]
    assert not offenders, (
        "ad-hoc telemetry containers outside pyabc_tpu/observability/ "
        "(add a named span or metric instrument instead):\n"
        + "\n".join(offenders)
    )
