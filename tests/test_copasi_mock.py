"""BasicoModel driven against a mock ``basico`` module.

The real COPASI bindings are not installable here; this pins the exact
basico API call sequence the adapter relies on (load_model ->
get/set_parameters / get/set_global_quantities -> run_time_course ->
remove_datamodel, including cleanup on error), so API drift or a typo in
the adapter fails HERE rather than on a user's machine (the fake-qsub
pattern of test_sge.py applied to an in-process dependency).
"""
import sys
import types

import numpy as np
import pandas as pd
import pytest


class MockBasico(types.ModuleType):
    """Scriptable basico stand-in recording every call."""

    def __init__(self, reaction_params=("k1",), global_quantities=("beta",)):
        super().__init__("basico")
        self.calls = []
        self._reaction_params = set(reaction_params)
        self._globals = set(global_quantities)
        self.removed = []

    def load_model(self, path):
        self.calls.append(("load_model", path))
        return {"path": path, "id": len(self.calls)}

    def get_parameters(self, key, model=None):
        self.calls.append(("get_parameters", key))
        if key in self._reaction_params:
            return pd.DataFrame({"name": [key], "value": [1.0]})
        return None

    def set_parameters(self, key, initial_value=None, model=None):
        self.calls.append(("set_parameters", key, initial_value))

    def get_global_quantities(self, key, model=None):
        self.calls.append(("get_global_quantities", key))
        if key in self._globals:
            return pd.DataFrame({"name": [key], "initial_value": [0.5]})
        return None

    def set_global_quantities(self, key, initial_value=None, model=None):
        self.calls.append(("set_global_quantities", key, initial_value))

    def run_time_course(self, duration=None, intervals=None, method=None,
                        model=None):
        self.calls.append(("run_time_course", duration, intervals, method))
        t = np.linspace(0.0, duration, intervals + 1)
        return pd.DataFrame({"S": np.exp(-t), "P": 1.0 - np.exp(-t)})

    def remove_datamodel(self, dm):
        self.calls.append(("remove_datamodel",))
        self.removed.append(dm)


@pytest.fixture
def mock_basico(monkeypatch, tmp_path):
    mod = MockBasico()
    monkeypatch.setitem(sys.modules, "basico", mod)
    model_file = tmp_path / "decay.cps"
    model_file.write_text("<COPASI/>")
    return mod, str(model_file)


def test_sample_call_sequence_and_outputs(mock_basico):
    mod, model_file = mock_basico
    from pyabc_tpu.copasi import BasicoModel

    m = BasicoModel(model_file, duration=10.0, n_points=6,
                    method="stochastic")
    out = m.sample({"k1": 2.5, "beta": 0.1})

    assert out.keys() == {"S", "P"}
    assert out["S"].shape == (6,) and out["S"].dtype == np.float64

    names = [c[0] for c in mod.calls]
    assert names[0] == "load_model"
    assert names[-1] == "remove_datamodel", "datamodel leaked"
    # k1 is a reaction parameter: set via set_parameters, NOT globals
    assert ("set_parameters", "k1", 2.5) in mod.calls
    assert not any(c[0] == "set_global_quantities" and c[1] == "k1"
                   for c in mod.calls)
    # beta is a global quantity: set via set_global_quantities
    assert ("set_global_quantities", "beta", 0.1) in mod.calls
    # n_points=6 -> intervals=5; method forwarded
    assert ("run_time_course", 10.0, 5, "stochastic") in mod.calls


def test_outputs_filter_selects_columns(mock_basico):
    mod, model_file = mock_basico
    from pyabc_tpu.copasi import BasicoModel

    m = BasicoModel(model_file, duration=4.0, n_points=3, outputs=["P"])
    out = m.sample({"k1": 1.0})
    assert list(out.keys()) == ["P"]


def test_unknown_parameter_raises_and_still_cleans_up(mock_basico):
    mod, model_file = mock_basico
    from pyabc_tpu.copasi import BasicoModel

    m = BasicoModel(model_file)
    with pytest.raises(KeyError, match="neither a reaction parameter"):
        m.sample({"nope": 1.0})
    assert mod.removed, "remove_datamodel must run on the error path"


def test_model_runs_inside_abc_loop(mock_basico):
    """The adapter as a real Model in a (tiny) ABC run: integration over
    SimpleModel-style dict summary statistics."""
    mod, model_file = mock_basico
    import pyabc_tpu as pt
    from pyabc_tpu.copasi import BasicoModel

    model = BasicoModel(model_file, duration=5.0, n_points=4)
    obs = model.sample({"k1": 1.0})
    np.random.seed(0)
    abc = pt.ABCSMC(
        model, pt.Distribution(k1=pt.RV("uniform", 0.5, 1.0)),
        pt.PNormDistance(p=2), population_size=20,
        eps=pt.QuantileEpsilon(initial_epsilon=1.0, alpha=0.5),
        sampler=pt.SingleCoreSampler(),
    )
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=2)
    assert h.n_populations == 2
