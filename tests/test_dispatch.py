"""Dispatch-engine tests (round 12): the single async dispatch engine
(`pyabc_tpu/inference/dispatch.py`) behind the fused path.

Covers the engine-level guarantees the three-loop refactor must keep:

- ``drain_join`` error paths: a background-drain failure re-raises on
  join (not silently-partial History), a double join is a no-op, a
  never-run object's join is a no-op;
- speculative rollback: a run whose fetch pipeline dispatched chunks
  PAST a stopping-rule hit discards them unpersisted — History is
  bit-identical to a minimally-speculative run of the same seed — and
  the rollback is counted (``pyabc_tpu_speculative_rollbacks_total``);
- the per-run sync budget: ``syncs_per_run <= chunks + O(1)`` holds and
  is exported (``pyabc_tpu_syncs_per_run`` gauge, engine snapshot,
  ``/api/observability`` dispatch block).
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.observability import MetricsRegistry, observability_snapshot

NOISE_SD = 0.5
X_OBS = 1.0


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss_dispatch")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _make(seed=81, pop=200, G=3, depth=3, metrics=None, **kwargs):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(
        _gauss_model(), prior, pt.PNormDistance(p=2), population_size=pop,
        eps=pt.MedianEpsilon(), seed=seed, fused_generations=G,
        fetch_pipeline_depth=depth,
        **({"metrics": metrics} if metrics is not None else {}),
        **kwargs,
    )
    abc.new("sqlite://", {"x": X_OBS})
    return abc


def _history_arrays(h):
    """Everything a bit-identity claim covers: epsilon trail plus every
    generation's (theta, weight, distance) arrays."""
    pops = h.get_all_populations().query("t >= 0")
    out = {"eps": pops["epsilon"].to_numpy()}
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        out[f"theta_{t}"] = df["theta"].to_numpy()
        out[f"w_{t}"] = np.asarray(w)
        out[f"d_{t}"] = h.get_weighted_distances(
            int(t))["distance"].to_numpy()
    return out


# ------------------------------------------------- drain_join error paths

def test_drain_join_reraises_background_drain_error():
    """An exception on the DRAIN thread (engine state DRAIN) must not
    leave a silently partial History: drain_join re-raises it, and a
    second join is a clean no-op (the error is consumed)."""
    abc = _make(seed=83)
    abc.drain_async = True

    boom = RuntimeError("injected drain-side failure")
    real_done = abc.history.done

    def failing_done():
        raise boom

    # history.done() only runs in the engine's _complete(); on a
    # drain_async run that is the drain thread's last act — so the
    # failure happens strictly in the background
    abc.history.done = failing_done
    abc.run(max_nr_populations=9)
    with pytest.raises(RuntimeError, match="injected drain-side"):
        abc.drain_join()
    # the error was consumed: a second join is a no-op, not a re-raise
    abc.drain_join()
    assert abc._drain_error is None
    abc.history.done = real_done
    abc.history.done()


def test_drain_join_double_and_fresh_noop():
    """drain_join is idempotent after a clean drain, and a no-op on an
    object that never ran (no drain thread, no error)."""
    abc = _make(seed=84)
    abc.drain_async = True
    h = abc.run(max_nr_populations=9)
    abc.drain_join()
    assert abc._drain_thread is None
    abc.drain_join()  # second join: no thread, no error, no exception
    assert h.n_populations == 9

    fresh = _make(seed=85)
    fresh.drain_join()  # never ran: nothing to join
    assert fresh._drain_thread is None and fresh._drain_error is None


# ------------------------------------- speculative rollback bit-identity

def test_speculative_rollback_history_bit_identical():
    """A stopping-rule hit (minimum_epsilon) lands mid-schedule while
    the engine has speculative chunks in flight; they are rolled back
    unpersisted. The History must be BIT-identical to a run of the same
    seed with the minimal pipeline (depth 1): same epsilon trail, same
    per-generation theta/weight/distance arrays, same generation count —
    speculation may never change results, only hide latency."""
    # reference trail to place the threshold mid-run (generation ~4 of 12)
    probe = _make(seed=77, G=2, depth=1)
    h_probe = probe.run(max_nr_populations=6)
    eps_trail = h_probe.get_all_populations().query(
        "t >= 0")["epsilon"].to_numpy()
    assert len(eps_trail) >= 4
    min_eps = float(eps_trail[3])  # stop once eps_used <= trail[3]

    reg_spec = MetricsRegistry()
    spec = _make(seed=77, G=2, depth=4, metrics=reg_spec)
    spec.adopt_device_context(probe)
    h_spec = spec.run(minimum_epsilon=min_eps, max_nr_populations=12)
    eng = spec._engine
    assert eng is not None
    # the 12-generation schedule at G=2 keeps up to 4 chunks in flight;
    # the stop at ~generation 4 must have discarded at least one
    assert eng.speculative_rollbacks >= 1
    assert reg_spec.snapshot()[
        "pyabc_tpu_speculative_rollbacks_total"] >= 1

    ref = _make(seed=77, G=2, depth=1)
    ref.adopt_device_context(probe)
    h_ref = ref.run(minimum_epsilon=min_eps, max_nr_populations=12)

    a, b = _history_arrays(h_spec), _history_arrays(h_ref)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(
            a[k], b[k], err_msg=f"speculative run diverged at {k}"
        )
    # and nothing past the stop persisted: both stopped where the probe
    # trail says the threshold was crossed
    assert h_spec.n_populations == h_ref.n_populations <= 6


# ----------------------------------------------------------- sync budget

def test_sync_budget_and_snapshot():
    """The engine's per-run sync budget holds on a clean fused run
    (`syncs_per_run <= chunks + O(1)`, asserted through
    SyncLedger.budget_report), the gauge is exported, and the engine's
    state rides the process observability snapshot."""
    reg = MetricsRegistry()
    abc = _make(seed=86, metrics=reg)
    h = abc.run(max_nr_populations=9)
    assert h.n_populations == 9
    eng = abc._engine
    report = eng.sync_budget_report()
    assert report["ok"], (report, abc.sync_ledger.by_kind())
    assert report["syncs"] <= report["chunks"] + 8
    assert report["chunks"] == eng.chunks_processed >= 1
    snap = reg.snapshot()
    assert snap["pyabc_tpu_syncs_per_run"] == report["syncs"]
    # engine snapshot reaches the process-wide observability snapshot
    # (the /api/observability "dispatch" block) while the engine lives
    snap_proc = observability_snapshot()
    states = [d.get("state") for d in snap_proc["dispatch"]]
    assert "done" in states
    # the gauge also lands on the process-wide registry, so dashboards
    # and the broker-status path see it without the run's registry
    assert snap_proc["metrics"][
        "pyabc_tpu_syncs_per_run"] == report["syncs"]


def test_sync_budget_strict_mode_raises(monkeypatch):
    """Under PYABC_TPU_SYNC_BUDGET_STRICT a budget violation is fatal —
    the bench dispatch lane and CI run with the invariant armed."""
    monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
    abc = _make(seed=87)
    # poison the ledger with per-chunk-looking noise far past any O(1)
    # allowance BEFORE the run so _complete() sees a violation
    for _ in range(64):
        abc.sync_ledger.record("rogue_per_chunk_sync")
    with pytest.raises(RuntimeError, match="sync budget exceeded"):
        abc.run(max_nr_populations=5)
    # the run still flushed/persisted what it had (no silent loss)
    assert abc.history.n_populations >= 1
