"""Late-generation numerical robustness of the f32 device path.

The device kernels carry log importance weights and distances in float32
with float64 host post-processing (exp-normalization, covariance refits).
The concern (VERDICT round 1, weak #8): as epsilon shrinks, the accepted
region collapses and f32 log-weight resolution could degrade the posterior.
These tests demonstrate f32 suffices deep into the schedule by checking the
device path against (a) the analytic posterior and (b) the float64 scalar
host oracle at matched small thresholds, plus direct weight-health
invariants (finite, non-degenerate effective sample size).
"""
import jax
import numpy as np
import pandas as pd
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)

# deep schedule: eps well below the posterior sd (0.447), into the regime
# where acceptance is rare and transition/prior density ratios get extreme
TIGHT_EPS = [2.0, 1.0, 0.5, 0.25, 0.12, 0.06, 0.03]


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _posterior_stats(h, m=0):
    df, w = h.get_distribution(m)
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(max(np.sum(df["theta"] ** 2 * w) - mu**2, 0.0)))
    ess = float(1.0 / np.sum((w / w.sum()) ** 2))
    return mu, sd, ess, np.asarray(w, np.float64)


@pytest.mark.parametrize("fused", [True, False])
def test_f32_device_weights_healthy_at_small_eps(fused):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=400, eps=pt.ListEpsilon(TIGHT_EPS),
                    seed=41, fused_generations=8 if fused else 1)
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=len(TIGHT_EPS))
    assert h.n_populations == len(TIGHT_EPS)
    mu, sd, ess, w = _posterior_stats(h)
    # weights finite and non-degenerate deep in the schedule
    assert np.isfinite(w).all() and (w >= 0).all()
    assert ess > 40, f"effective sample size collapsed: {ess}"
    # at eps << posterior sd the ABC posterior approaches the true one
    assert mu == pytest.approx(POST_MU, abs=0.15)
    assert sd == pytest.approx(np.sqrt(POST_VAR), abs=0.12)


@pytest.mark.slow
def test_f32_device_matches_f64_host_oracle_at_small_eps():
    """Device f32 kernel vs the scalar float64 host closure (the oracle
    path) at an identical tight schedule: posterior moments must agree
    within Monte-Carlo error, so f32 carries no visible bias."""
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    eps = TIGHT_EPS[:6]

    abc_dev = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                        population_size=300, eps=pt.ListEpsilon(eps),
                        seed=42)
    abc_dev.new("sqlite://", {"x": X_OBS})
    h_dev = abc_dev.run(max_nr_populations=len(eps))

    np.random.seed(43)
    abc_host = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                         population_size=300, eps=pt.ListEpsilon(eps),
                         sampler=pt.SingleCoreSampler(), seed=43)
    abc_host.new("sqlite://", {"x": X_OBS})
    h_host = abc_host.run(max_nr_populations=len(eps))

    mu_d, sd_d, ess_d, _ = _posterior_stats(h_dev)
    mu_h, sd_h, ess_h, _ = _posterior_stats(h_host)
    assert mu_d == pytest.approx(mu_h, abs=0.15)
    assert sd_d == pytest.approx(sd_h, abs=0.1)
    # both healthy
    assert ess_d > 30 and ess_h > 30


def test_fused_deep_schedule_f32_weights_match_f64_recomputation():
    """MedianEpsilon driven deep: recompute every stored importance weight
    of the LAST generation in float64 numpy/scipy (prior / f64-refit KDE
    mixture of the previous population) and compare with what the f32
    device kernel produced. This is the direct evidence that f32 carries
    the weight math even where the schedule gets extreme — heavy-weight
    outlier particles at tiny eps are genuine SMC tail-impoverishment
    (identical in f64), not a precision artifact."""
    import pandas as pd
    from scipy.stats import norm as scipy_norm

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    # f32 wire: this test isolates f32 DEVICE math vs a f64 oracle over
    # the persisted rows; the default f16 fetch narrowing (audited in
    # test_fetch_precision.py) would alias into the 5e-4 comparison
    abc = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                    population_size=300, eps=pt.MedianEpsilon(), seed=44,
                    fused_generations=6, fetch_dtype="float32")
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=12)
    # the run may legitimately stop short when a deep generation misses its
    # target within the round budget (acceptance at the noise floor)
    assert h.n_populations >= 8
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert eps[-1] < 0.05  # genuinely deep
    T = h.n_populations - 1
    df_prev, w_prev = h.get_distribution(0, T - 1)
    df_last, w_last = h.get_distribution(0, T)
    th_prev = df_prev["theta"].to_numpy()
    th_last = df_last["theta"].to_numpy()
    w_last = np.asarray(w_last, np.float64)
    assert np.isfinite(w_last).all() and (w_last >= 0).all()
    w_last = w_last / w_last.sum()
    # float64 oracle: prior / KDE-mixture density, KDE refit in float64
    tr = pt.MultivariateNormalTransition()
    tr.fit(pd.DataFrame({"theta": th_prev}),
           np.asarray(w_prev) / np.sum(w_prev))
    q = np.asarray([tr.pdf(pd.Series({"theta": v})) for v in th_last])
    w64 = scipy_norm.pdf(th_last, 0.0, PRIOR_SD) / q
    w64 = w64 / w64.sum()
    np.testing.assert_allclose(w_last, w64, rtol=5e-4, atol=1e-7)


def test_mixture_logpdf_stable_far_from_origin():
    """The MXU-decomposed KDE mixture density expands the Mahalanobis
    form around the population MEAN: a posterior concentrated at
    |mean| >> bandwidth (here 1e3 vs 1e-2) must still match the f64 host
    KDE — the origin-centered expansion loses ~1e10 of f32 precision
    here and returns garbage."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    n, d = 256, 2
    center = np.array([1.0e3, -2.0e3])
    X = pd.DataFrame(center + rng.normal(0, 1e-2, (n, d)),
                     columns=["a", "b"])
    w = rng.uniform(0.5, 1.0, n)
    w = w / w.sum()
    tr = pt.MultivariateNormalTransition()
    tr.fit(X, w)
    params = {k: jnp.asarray(v) for k, v in tr.device_params().items()}
    q = (center + rng.normal(0, 1e-2, (64, d))).astype(np.float32)
    dev = jax.vmap(
        lambda th: pt.MultivariateNormalTransition.device_logpdf(th, params)
    )(jnp.asarray(q))
    host = np.log(np.maximum(
        np.asarray(tr.pdf(pd.DataFrame(q, columns=["a", "b"])), np.float64),
        1e-300,
    ))
    np.testing.assert_allclose(np.asarray(dev), host, rtol=2e-3, atol=5e-2)


def test_kernel_logdensity_f32_vs_f64_at_tiny_scales():
    """Stochastic-kernel log-density SUMS at tiny kernel scales (the
    T -> 1 regime of Daly/Ess schedules): the f32 device twin must match
    an f64 oracle both absolutely and — what acceptance actually consumes
    — in the DIFFERENCES between candidates (SURVEY §7.3.5 silent-bias
    risk)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    S = 25
    var = np.full(S, (1e-3) ** 2)  # sd 1e-3 per statistic
    x0 = rng.normal(0.0, 1.0, S)
    # candidates spread from "right on top of x0" to a few kernel sds off
    xs = x0[None, :] + rng.normal(0.0, 3e-3, (64, S))

    kern = pt.IndependentNormalKernel(var=var)
    kern.initialize(0, x_0={str(i): x0[i] for i in range(S)})
    fn = kern.device_fn(kern.spec)
    params = jnp.asarray(kern.device_params(0), jnp.float32)
    dev = np.asarray([
        float(fn(jnp.asarray(x, jnp.float32),
                 jnp.asarray(x0, jnp.float32), params))
        for x in xs
    ])

    d64 = (xs - x0[None, :]).astype(np.float64)
    oracle = -0.5 * np.sum(
        np.log(2 * np.pi * var)[None, :] + d64 * d64 / var[None, :], axis=1
    )
    # magnitudes run to O(100s); absolute agreement to ~1e-3 of that
    np.testing.assert_allclose(dev, oracle, rtol=1e-5, atol=5e-3)
    # pairwise differences (what exp((v - c)/T) consumes) stay faithful
    dd = dev - dev[0]
    oo = oracle - oracle[0]
    np.testing.assert_allclose(dd, oo, rtol=1e-4, atol=1e-2)


def test_fused_noisy_daly_to_t1_tiny_kernel_matches_analytic():
    """Daly schedule annealed ALL the way to T=1 with a tiny noise kernel
    (sd 0.02 on a unit prior): at T=1 stochastic ABC targets the exact
    conjugate posterior, so any f32 bias in the in-kernel log-density /
    pdf-norm / temperature recursion shows up as a shifted or inflated
    posterior."""
    from pyabc_tpu.epsilon.temperature import DalyScheme

    kernel_sd = 0.02
    prior_sd = 1.0
    x_obs = 0.8

    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, prior_sd))
    abc = pt.ABCSMC(
        model, prior,
        pt.IndependentNormalKernel(var=[kernel_sd**2]),
        population_size=300,
        eps=pt.Temperature(schemes=[DalyScheme()]),
        acceptor=pt.StochasticAcceptor(),
        seed=29, fused_generations=4,
    )
    abc.new("sqlite://", {"x": x_obs})
    h = abc.run(max_nr_populations=18)
    # the schedule must actually REACH the exact-posterior temperature
    final_T = h.get_all_populations().query("t >= 0")["epsilon"].iloc[-1]
    assert final_T == pytest.approx(1.0, abs=1e-6)
    post_var = 1.0 / (1 / prior_sd**2 + 1 / kernel_sd**2)
    post_mu = post_var * x_obs / kernel_sd**2
    df, w = h.get_distribution(0, h.max_t)
    w = np.asarray(w, np.float64)
    assert np.isfinite(w).all() and (w >= 0).all()
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
    assert mu == pytest.approx(post_mu, abs=0.012)
    assert sd == pytest.approx(np.sqrt(post_var), rel=0.35)
    # weights must not have collapsed to a handful of particles
    ess = 1.0 / np.sum(w**2)
    assert ess > 30


def test_local_transition_mixture_logpdf_stable_bimodal():
    """LocalTransition's per-component mixture density must stay faithful
    to the host f64 KDE in its TARGET regime — fine local bandwidths over
    a widely spread / multimodal population — where a mean-centered
    quadratic expansion (fine for the shared-covariance MVN) loses
    ~(spread/bandwidth)^2 of f32 precision. Guards the deliberate
    diff-form implementation."""
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    n, d = 128, 2
    modes = np.array([[500.0, 500.0], [-500.0, -500.0]])
    which = rng.integers(0, 2, n)
    X = pd.DataFrame(modes[which] + rng.normal(0, 0.05, (n, d)),
                     columns=["a", "b"])
    w = rng.uniform(0.5, 1.0, n)
    w = w / w.sum()
    tr = pt.LocalTransition()
    tr.fit(X, w)
    params = {k: jnp.asarray(v) for k, v in tr.device_params().items()}
    # queries AT the modes: maha is O(1) there, so any catastrophic
    # cancellation in the mixture terms shows up directly
    qwhich = rng.integers(0, 2, 32)
    q = (modes[qwhich] + rng.normal(0, 0.05, (32, d))).astype(np.float32)
    dev = jax.vmap(
        lambda th: pt.LocalTransition.device_logpdf(th, params)
    )(jnp.asarray(q))
    host = np.log(np.maximum(
        np.asarray(tr.pdf(pd.DataFrame(q, columns=["a", "b"])),
                   np.float64), 1e-300,
    ))
    np.testing.assert_allclose(np.asarray(dev), host, rtol=2e-3, atol=0.1)
