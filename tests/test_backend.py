"""Storage backend seam: sqlite stays raw; postgres translation is unit-
tested against a recording fake DB-API driver (no server needed — the
SGE-stub contract pattern)."""
import sqlite3
import sys
import types

import pytest

from pyabc_tpu.storage import History
from pyabc_tpu.storage.backend import (
    PgConnection,
    explicit_id_insert_table,
    split_script,
    translate_ddl,
    translate_sql,
    wants_returning_id,
)
from pyabc_tpu.storage.history import _SCHEMA


def test_sqlite_urls_return_raw_connection(tmp_path):
    h = History(f"sqlite:///{tmp_path}/t.db")
    assert isinstance(h._conn, sqlite3.Connection)
    assert h._dialect.name == "sqlite"


def test_postgres_url_gated_without_psycopg2(monkeypatch):
    monkeypatch.setitem(sys.modules, "psycopg2", None)
    with pytest.raises(ImportError, match="psycopg2"):
        History("postgresql://user@host/db")


def test_sql_translation():
    assert translate_sql("SELECT * FROM t WHERE a = ? AND b = ?") == \
        "SELECT * FROM t WHERE a = %s AND b = %s"


def test_ddl_translation():
    ddl = translate_ddl(_SCHEMA)
    assert "AUTOINCREMENT" not in ddl
    assert "BIGSERIAL PRIMARY KEY" in ddl
    assert " BLOB" not in ddl and " BYTEA" in ddl
    # every schema statement survives the split
    assert len(split_script(ddl)) == len(split_script(_SCHEMA))


def test_returning_id_heuristic():
    assert wants_returning_id("INSERT INTO models (population_id) VALUES (?)")
    # explicit-id batched inserts must NOT get RETURNING (executemany)
    assert not wants_returning_id(
        "INSERT INTO particles (id, model_id, w, distance) VALUES (?,?,?,?)"
    )
    assert not wants_returning_id("SELECT 1")


class _FakeCursor:
    def __init__(self, log):
        self.log = log

    def execute(self, sql, params=()):
        self.log.append(("execute", sql, tuple(params)))

    def executemany(self, sql, seq):
        self.log.append(("executemany", sql, len(list(seq))))

    def fetchone(self):
        self.log.append(("fetchone",))
        return (42,)

    def fetchall(self):
        return []

    description = None

    def close(self):
        pass


class _FakeConn:
    def __init__(self):
        self.log = []

    def cursor(self):
        return _FakeCursor(self.log)

    def commit(self):
        self.log.append(("commit",))

    def rollback(self):
        self.log.append(("rollback",))


def test_explicit_id_table_detection():
    assert explicit_id_insert_table(
        "INSERT INTO particles (id, model_id) VALUES (?,?)") == "particles"
    assert explicit_id_insert_table(
        "INSERT INTO models (m) VALUES (?)") is None


def test_pg_adapter_translates_and_emulates_lastrowid():
    fake = _FakeConn()
    conn = PgConnection(fake)
    cur = conn.cursor()
    cur.execute("BEGIN IMMEDIATE")
    # BEGIN IMMEDIATE's write lock maps to BEGIN + an advisory xact lock
    assert fake.log[-2] == ("execute", "BEGIN", ())
    assert "pg_advisory_xact_lock" in fake.log[-1][1]
    cur.execute("INSERT INTO models (m) VALUES (?)", (3,))
    assert fake.log[-2] == (
        "execute", "INSERT INTO models (m) VALUES (%s) RETURNING id", (3,))
    assert fake.log[-1] == ("fetchone",)
    assert cur.lastrowid == 42
    cur.executemany(
        "INSERT INTO particles (id, model_id, w, distance) VALUES (?,?,?,?)",
        [(1, 1, 0.5, 0.1)],
    )
    # explicit-id batch insert resynchronizes the table's sequence
    assert "setval" in fake.log[-1][1] and "particles" in fake.log[-1][1]
    assert fake.log[-2][1].count("%s") == 4
    conn.executescript(_SCHEMA)
    assert fake.log[-1] == ("commit",)
    executed_ddl = [e for e in fake.log if e[0] == "execute"
                    and "CREATE" in e[1]]
    assert len(executed_ddl) == len(split_script(_SCHEMA))
    assert all("AUTOINCREMENT" not in e[1] for e in executed_ddl)
