"""Device-native learned summary statistics (ISSUE 20).

The tentpole contract: Fearnhead-Prangle predictors fit at chunk
boundaries INSIDE the multigen kernel (weighted ridge on the accepted
reservoir, riding the theta all-gather the cadence refit already pays),
the fitted params ride the chunk carry, and every consumer — fused
loop, sharded kernel at any divisor width, segmented early-reject
engine, packed fetch — sees only transformed C'-dim statistics.

Asserted here:
- the in-kernel ``ridge_fit`` is the host ``LinearPredictor.fit``'s
  traceable twin (f32-vs-f64 parity), and ``mirror_fitted_params``
  round-trips the carried values bit-identically;
- a blown float32 fit (ill-conditioned Gram vs alpha) degrades to
  carrying the previous transform instead of poisoning the run;
- mesh runs are bit-identical to virtual shards at widths {1, 2, 4, 8},
  including composed sharded + segmented early-reject;
- the capability gates LIFT for linear non-adaptive configs and keep
  actionable reasons for everything still host-side (GP,
  ModelSelection, Lasso, MLP-under-sharding, host cadence control),
  with the fallback recorded in telemetry;
- the strict sync budget holds and matches the identity run up to the
  generation-0 seed fit's single collect.

conftest forces 8 virtual CPU devices (the CI ``mesh``/``sumstat``
rig), so mesh widths here are real shard_map sub-meshes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import pyabc_tpu as pt
from pyabc_tpu.observability.metrics import (
    SUMSTAT_DIM_GAUGE,
    SUMSTAT_DIM_REDUCED_GAUGE,
    SUMSTAT_REFITS_TOTAL,
    MetricsRegistry,
)
from pyabc_tpu.ops.fit import keep_if_finite, ridge_fit
from pyabc_tpu.sumstat.device import device_fit_plan, mirror_fitted_params

pytestmark = pytest.mark.mesh

NOISE_SD = 0.3
POST_MU = 1.0 * (2 / NOISE_SD**2) / (1.0 + 2 / NOISE_SD**2)


def _mesh(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} virtual cpu devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), axis_names=("particles",))


def _fp_model():
    @pt.JaxModel.from_function(["theta"], name="fp_device")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        sig = theta[0] + NOISE_SD * jax.random.normal(k1, (2,))
        noise = 5.0 * jax.random.normal(k2, (4,))
        return {"sig": sig, "noise": noise}

    return model


def _linear_dist(alpha=1e-6):
    return pt.PNormDistance(
        p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor(alpha=alpha)))


def _make(seed=41, pop=128, G=2, mesh=None, sharded=None, dist=None,
          **kwargs):
    abc = pt.ABCSMC(
        _fp_model(), pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        dist if dist is not None else _linear_dist(),
        population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
        mesh=mesh, sharded=sharded, fused_generations=G, **kwargs,
    )
    abc.new("sqlite://",
            {"sig": np.asarray([1.0, 1.0]), "noise": np.zeros(4)})
    return abc


def _history_arrays(h):
    pops = h.get_all_populations().query("t >= 0")
    out = {"eps": pops["epsilon"].to_numpy()}
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        out[f"theta_{t}"] = df["theta"].to_numpy()
        out[f"w_{t}"] = np.asarray(w)
        out[f"d_{t}"] = h.get_weighted_distances(
            int(t))["distance"].to_numpy()
    return out


# ------------------------------------------------- device-vs-host fit

class TestFitParity:
    def test_ridge_fit_matches_host_linear(self):
        """ops.fit.ridge_fit (f32, traced) against LinearPredictor.fit
        (f64, numpy) on the same weighted problem — the kernel twin
        contract."""
        rng = np.random.default_rng(7)
        n, S, d = 300, 6, 2
        x = rng.normal(size=(n, S)) * [1, 2, 3, 4, 5, 6]
        y = x[:, :d] @ rng.normal(size=(d, d)) + 0.1 * rng.normal(
            size=(n, d))
        w = rng.random(n) + 0.1

        host = pt.LinearPredictor(alpha=0.5)
        host.fit(x, y, w)
        hp = {k: np.asarray(v) for k, v in host.device_params().items()}

        dev = jax.jit(ridge_fit, static_argnames="alpha")(
            jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
            jnp.asarray(w, jnp.float32), jnp.ones(n, bool), alpha=0.5)
        for k in ("W", "b", "mu", "sd"):
            np.testing.assert_allclose(
                np.asarray(dev[k]), hp[k], rtol=2e-4, atol=2e-4,
                err_msg=f"device ridge_fit diverged from host at {k}")

    def test_ridge_fit_masked_rows_contribute_nothing(self):
        rng = np.random.default_rng(8)
        n, S = 64, 4
        x = rng.normal(size=(n, S)).astype(np.float32)
        y = rng.normal(size=(n, 1)).astype(np.float32)
        w = rng.random(n).astype(np.float32)
        mask = np.arange(n) < 48
        base = ridge_fit(jnp.asarray(x[:48]), jnp.asarray(y[:48]),
                         jnp.asarray(w[:48]), jnp.ones(48, bool), 0.1)
        x[48:] = 1e6  # garbage beyond the accepted prefix
        masked = ridge_fit(jnp.asarray(x), jnp.asarray(y),
                           jnp.asarray(w), jnp.asarray(mask), 0.1)
        for k in base:
            np.testing.assert_allclose(
                np.asarray(masked[k]), np.asarray(base[k]),
                rtol=1e-5, atol=1e-6)

    def test_mirror_round_trip_bit_identical(self):
        """mirror_fitted_params stores the fetched f32 values as-is, so
        a resume-rebuilt carry equals the carried device operands
        bitwise — the preempt-matrix contract's foundation."""
        dist = _linear_dist()
        rng = np.random.default_rng(9)
        ssp = {"W": rng.normal(size=(6, 1)).astype(np.float32),
               "b": rng.normal(size=(1,)).astype(np.float32),
               "mu": rng.normal(size=(6,)).astype(np.float32),
               "sd": (rng.random(6) + 0.5).astype(np.float32)}
        mirror_fitted_params(dist, ssp, t=3)
        assert dist.sumstat._last_fit_t == 3
        back = dist.sumstat.predictor.device_params()
        for k, v in ssp.items():
            np.testing.assert_array_equal(np.asarray(back[k]), v)

    def test_keep_if_finite_guard(self):
        old = {"W": jnp.ones((2, 1)), "b": jnp.zeros((1,))}
        good = {"W": 2 * jnp.ones((2, 1)), "b": jnp.ones((1,))}
        kept, ok = keep_if_finite(good, old)
        assert bool(ok)
        np.testing.assert_array_equal(np.asarray(kept["W"]),
                                      np.asarray(good["W"]))
        bad = {"W": jnp.full((2, 1), jnp.nan), "b": jnp.ones((1,))}
        kept, ok = keep_if_finite(bad, old)
        assert not bool(ok)
        for k in old:
            np.testing.assert_array_equal(np.asarray(kept[k]),
                                          np.asarray(old[k]))


# ------------------------------------------------- device mode runs

class TestDeviceFitRuns:
    def test_linear_device_mode_counts_and_telemetry(self):
        reg = MetricsRegistry()
        abc = _make(seed=43, G=2, metrics=reg)
        h = abc.run(max_nr_populations=6)
        plan = abc._sumstat_device_plan
        assert plan is not None and plan["kind"] == "linear"
        # 6 gens as gen0 + chunks of 2: the run-ending chunk fires no
        # boundary fit, every other boundary does
        assert reg.counter(SUMSTAT_REFITS_TOTAL).value >= 1
        assert reg.gauge(SUMSTAT_DIM_GAUGE).value == 6
        assert reg.gauge(SUMSTAT_DIM_REDUCED_GAUGE).value == 1
        blocks = [(h.get_telemetry(t) or {}).get("sumstat")
                  for t in range(h.n_populations)]
        block = next(b for b in blocks if b)
        assert block["mode"] == "device"
        assert block["kind"] == "linear"
        assert block["dim_raw"] == 6
        assert block["dim_reduced"] == 1
        # posterior sanity on the conjugate reference
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert abs(mu - POST_MU) < 0.25

    def test_blown_fit_keeps_run_alive(self):
        """Regression for the float32 ridge NaN: at S=128 correlated
        stats, alpha=1e-6 is below f32 noise on the ~n-scaled Gram and
        the solve goes non-finite. The kernel guard must keep the
        previous boundary's params (skipping the refit) instead of
        poisoning every subsequent distance and exhausting the health
        engine's rollback budget."""
        from pyabc_tpu.models import sir as sir_mod

        n_patches, n_obs = 8, 16
        abc = pt.ABCSMC(
            sir_mod.make_network_sir_model(
                n_patches=n_patches, n_obs=n_obs),
            sir_mod.network_sir_prior(), _linear_dist(alpha=1e-6),
            population_size=144, eps=pt.MedianEpsilon(), seed=11,
            fused_generations=2,
        )
        abc.new("sqlite://", sir_mod.observed_network_sir(
            n_patches=n_patches, n_obs=n_obs))
        h = abc.run(max_nr_populations=4)
        assert h.n_populations == 4
        assert abc._sumstat_device_plan is not None


# ------------------------------------------------- width bit-identity

@pytest.fixture(scope="module")
def virtual_reference():
    """sharded=8 WITHOUT a mesh: the canonical 8-shard reduction
    vmapped on one device."""
    abc = _make(seed=47, sharded=8)
    assert abc._sharded_n() == 8
    h = abc.run(max_nr_populations=6)
    assert abc._sumstat_device_plan is not None
    return _history_arrays(h)


class TestTransformBitIdentity:
    @pytest.mark.parametrize("width", [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        4,
        8,
    ])
    def test_mesh_bit_identical_to_virtual_shards(
            self, virtual_reference, width):
        """The fitted transform rides the carry as shard-replicated
        params and the boundary ridge solves on gathered replicated
        rows, so every mesh width computes the identical fit — learned
        statistics stay an execution choice, never a statistical
        one."""
        abc = _make(seed=47, mesh=_mesh(width), sharded=8)
        assert abc._sharded_n() == 8
        h = abc.run(max_nr_populations=6)
        assert abc._sumstat_device_plan is not None
        got = _history_arrays(h)
        assert set(got) == set(virtual_reference)
        for k in got:
            np.testing.assert_array_equal(
                got[k], virtual_reference[k],
                err_msg=f"width {width} diverged from virtual shards "
                        f"at {k} under the learned transform")

    def test_sharded_segmented_composed_bit_identity(self):
        """PredictorSumstat(LinearPredictor) on the sharded multigen
        kernel WITH the segmented early-reject engine: prefix bounds
        evaluate in transformed C' space and a real 4-device mesh stays
        bit-identical to virtual shards.

        Retirement COUNT is data-dependent and not asserted: when every
        remaining segment's coefficient block is surjective onto the
        C'-dim transformed space, the sound lower bound is 0 (any
        transformed value still reachable) and nothing retires — the
        engine must still run, resolve every lane, and change no
        result."""
        from pyabc_tpu.models import gillespie as g

        def make(mesh):
            abc = pt.ABCSMC(
                g.make_birth_death_model(n_leaps=100, n_obs=20,
                                         segments=5),
                g.birth_death_prior(), _linear_dist(),
                population_size=64, eps=pt.MedianEpsilon(), seed=73,
                early_reject="auto", mesh=mesh, sharded=8,
                fused_generations=3,
            )
            abc.new("sqlite://", g.observed_birth_death(
                n_leaps=100, n_obs=20, segments=5))
            return abc

        abc_v = make(None)
        h_v = abc_v.run(max_nr_populations=4)
        assert abc_v._sumstat_device_plan is not None

        abc_m = make(_mesh(4))
        h_m = abc_m.run(max_nr_populations=4)

        seg_resolved = sum(
            (h_m.get_telemetry(t) or {}).get("seg_resolved", 0)
            for t in range(h_m.n_populations))
        assert seg_resolved > 0, "early-reject engine not engaged"
        assert any("retired_early" in (h_m.get_telemetry(t) or {})
                   for t in range(h_m.n_populations))

        def arrays(h):
            pops = h.get_all_populations().query("t >= 0")
            out = {"eps": pops["epsilon"].to_numpy()}
            for t in pops["t"]:
                df, w = h.get_distribution(0, int(t))
                out[f"theta_{t}"] = df.to_numpy()
                out[f"w_{t}"] = np.asarray(w)
            return out

        a, b = arrays(h_m), arrays(h_v)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(
                a[k], b[k],
                err_msg=f"sharded+segmented learned transform diverged "
                        f"at {k}")


# ------------------------------------------------- capability gates

class TestGateLift:
    def test_sharded_gate_lifts_for_linear(self):
        abc = _make(seed=1)
        assert abc._sharded_incapable_reason(8) is None

    def test_sharded_gate_refuses_adaptive_sumstat(self):
        abc = _make(seed=1, dist=pt.AdaptivePNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())))
        reason = abc._sharded_incapable_reason(8)
        assert reason is not None
        assert "UNSHARDED device-fit path" in reason

    def test_sharded_gate_names_host_plan_reason(self):
        abc = _make(seed=1, dist=pt.PNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pt.GPPredictor())))
        reason = abc._sharded_incapable_reason(8)
        assert reason is not None
        assert "HOST-side" in reason and "GPPredictor" in reason

    @pytest.mark.parametrize("pred,sharded_n,frag", [
        (lambda: pt.ModelSelectionPredictor([pt.LinearPredictor()]),
         None, "cross-validated winner"),
        (lambda: pt.GPPredictor(), None, "host RNG"),
        (lambda: pt.LassoPredictor(), None, "ISTA"),
        (lambda: pt.MLPPredictor(), 8, "LINEAR device fits only"),
    ], ids=["model_selection", "gp", "lasso", "mlp_sharded"])
    def test_plan_refusal_reasons(self, pred, sharded_n, frag):
        d = pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(pred()))
        plan, reason = device_fit_plan(
            d, total_size=6, d_max=1, sharded_n=sharded_n)
        assert plan is None
        assert frag in reason

    def test_plan_refuses_host_cadence_control(self):
        d = pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
            pt.LinearPredictor(), fit_every=3))
        plan, reason = device_fit_plan(
            d, total_size=6, d_max=1, sharded_n=None)
        assert plan is None
        assert "fit_every=3" in reason

    def test_plan_resolves_linear_and_mlp(self):
        d = pt.PNormDistance(p=2,
                             sumstat=pt.PredictorSumstat(
                                 pt.LinearPredictor(alpha=0.25)))
        plan, reason = device_fit_plan(d, total_size=6, d_max=2,
                                       sharded_n=8)
        assert reason is None
        assert plan == {"kind": "linear", "out_dim": 2, "need": 8,
                        "alpha": 0.25}
        d = pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
            pt.MLPPredictor(n_steps=400), min_samples=20))
        plan, reason = device_fit_plan(d, total_size=6, d_max=2,
                                       sharded_n=None)
        assert reason is None
        assert plan["kind"] == "mlp"
        assert plan["need"] == 20
        assert plan["n_steps"] <= 100  # bounded boundary cost

    @staticmethod
    def _segmented_abc(dist):
        from pyabc_tpu.models import gillespie as g

        abc = pt.ABCSMC(
            g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5),
            g.birth_death_prior(), dist,
            population_size=64, eps=pt.MedianEpsilon(), seed=73,
            fused_generations=3,
        )
        abc.new("sqlite://", g.observed_birth_death(
            n_leaps=100, n_obs=20, segments=5))
        # the transformed-space prefix bound exists for FITTED params
        # only (the generation-0 host fit seeds them in a real run)
        rng = np.random.default_rng(0)
        abc.distance_function.sumstat.predictor.fit(
            rng.normal(size=(40, 20)), rng.normal(size=(40, 2)))
        abc.distance_function.sumstat._out_dim = 2
        return abc

    def test_early_reject_gate_lifts_for_linear(self):
        abc = self._segmented_abc(_linear_dist())
        assert abc._early_reject_incapable_reason(
            adaptive=False, stochastic=False, sumstat_mode=True,
            sharded_n=None) is None

    def test_early_reject_gate_refuses_adaptive_sumstat(self):
        """Adaptive scale + learned transform keeps the classic
        kernel: the transformed-space prefix bound is restricted to
        plain PNormDistance (the adaptive variant refits its weights
        from a scale reduction that itself needs the transformed rows
        — a circularity the host path resolves), so device_bound_fn
        refuses the composition upstream of the transform-cadence
        check."""
        abc = self._segmented_abc(pt.AdaptivePNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())))
        reason = abc._early_reject_incapable_reason(
            adaptive=True, stochastic=False, sumstat_mode=True,
            sharded_n=None)
        assert reason is not None
        assert "classic kernel" in reason

    def test_early_reject_gate_refuses_mlp(self):
        """Nonlinear transforms mix prefix entries with no per-prefix
        linear structure to project: device_bound_fn refuses them
        before the plan-kind check, so MLP keeps the classic kernel."""
        from pyabc_tpu.models import gillespie as g

        abc = pt.ABCSMC(
            g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5),
            g.birth_death_prior(),
            pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
                pt.MLPPredictor())),
            population_size=64, eps=pt.MedianEpsilon(), seed=73,
            fused_generations=3,
        )
        abc.new("sqlite://", g.observed_birth_death(
            n_leaps=100, n_obs=20, segments=5))
        reason = abc._early_reject_incapable_reason(
            adaptive=False, stochastic=False, sumstat_mode=True,
            sharded_n=None)
        assert reason is not None
        assert "classic kernel" in reason


# ------------------------------------------------- fallback telemetry

class TestFallbackTelemetry:
    @pytest.mark.parametrize("pred,frag", [
        (lambda: pt.GPPredictor(), "GPPredictor"),
        (lambda: pt.ModelSelectionPredictor(
            [pt.LinearPredictor(), pt.LassoPredictor()]),
         "cross-validated winner"),
    ], ids=["gp", "model_selection"])
    def test_host_predictors_fall_back_with_reason(self, pred, frag):
        """GP / ModelSelection stay host-refit: the run completes on
        the legacy path and the sumstat_device capability gate records
        WHY, with the telemetry block reporting host mode."""
        abc = _make(seed=53, pop=64, dist=pt.PNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pred())))
        h = abc.run(max_nr_populations=3)
        assert abc._sumstat_device_plan is None
        gates = {f["gate"] for f in abc._capability_fallbacks}
        assert "sumstat_device" in gates
        reasons = " ".join(
            f["reason"] for f in abc._capability_fallbacks)
        assert frag in reasons
        blocks = [(h.get_telemetry(t) or {}).get("sumstat")
                  for t in range(h.n_populations)]
        block = next(b for b in blocks if b)
        assert block["mode"] == "host"


# ------------------------------------------------- sync budget

class TestSyncBudget:
    def test_strict_budget_matches_identity(self, monkeypatch):
        """The in-kernel fit adds NO syncs: the fitted params ride the
        carry and the ridge solve rides the boundary the run already
        pays. The only delta vs an identity-sumstat run is the
        generation-0 HOST seed fit's single collect."""
        monkeypatch.setenv("PYABC_TPU_SYNC_BUDGET_STRICT", "1")
        ident = _make(seed=57, sharded=8, dist=pt.PNormDistance(p=2))
        ident.run(max_nr_populations=6)
        ident_rep = ident._engine.sync_budget_report()
        assert ident_rep["ok"], ident_rep

        learned = _make(seed=57, sharded=8)
        learned.run(max_nr_populations=6)
        rep = learned._engine.sync_budget_report()
        assert rep["ok"], rep
        assert rep["syncs"] <= ident_rep["syncs"] + 1


# ------------------------------------------------- posterior quality

class TestPosteriorQuality:
    def test_network_sir_learned_not_worse_than_identity(self):
        """ISSUE 20 acceptance: on the high-dim network SIR (S=128 raw
        stats), learned linear summaries at a matched budget give a
        posterior no worse than identity (RMSE of the posterior mean vs
        the true generating parameters, seed-matched tolerance).

        The scenario puts the SAME measurement noise in the simulator
        as in the observation (the Fearnhead-Prangle premise: the
        regression must train on data drawn like the observed data — a
        transform fit on noise-free stats mis-extrapolates to a noisy
        x0 and biases the posterior, measured at +0.25 RMSE on the
        deterministic variant). alpha=1.0 keeps the f32 normal
        equations conditioned at S=128; pop > S + 2 so the
        generation-0 seed fit fires; the chunk-boundary refits then
        localize the regression onto the posterior region (measured:
        RMSE 0.25 -> 0.056 -> 0.014 over 4/6/8 generations)."""
        from pyabc_tpu.models import sir as sir_mod

        n_patches, n_obs, pop, gens, noise = 8, 16, 256, 8, 30.0
        obs = sir_mod.observed_network_sir(
            n_patches=n_patches, n_obs=n_obs, noise_sd=noise)
        true = sir_mod.TRUE_PARS

        def rmse(dist):
            abc = pt.ABCSMC(
                sir_mod.make_network_sir_model(
                    n_patches=n_patches, n_obs=n_obs, noise_sd=noise),
                sir_mod.network_sir_prior(), dist,
                population_size=pop, eps=pt.MedianEpsilon(), seed=19,
                fused_generations=2,
            )
            abc.new("sqlite://", obs)
            h = abc.run(max_nr_populations=gens)
            df, w = h.get_distribution(0, h.max_t)
            err = [float(np.sum(df[k] * w)) - v
                   for k, v in true.items()]
            return float(np.sqrt(np.mean(np.square(err)))), abc

        rmse_id, _ = rmse(pt.PNormDistance(p=2))
        rmse_ln, abc_ln = rmse(_linear_dist(alpha=1.0))
        assert abc_ln._sumstat_device_plan is not None
        assert rmse_ln <= rmse_id + 0.02, (
            f"learned {rmse_ln:.4f} vs identity {rmse_id:.4f}")
