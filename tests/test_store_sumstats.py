"""Sum-stat retention policy (History.store_sum_stats) + kernel adoption.

``store_sum_stats=False`` / ``=k`` lets the History skip per-particle
summary statistics — on the fused device path the skipped generations avoid
the sumstat device->host fetch entirely (the dominant share of the chunk
payload). Parameters, weights and distances must be byte-identical to a
full-retention run of the same seed. ``ABCSMC.adopt_device_context`` reuses
a previous run's compiled kernels for repeated identical configurations
(bench.py's budget-spending loop).
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt

NOISE_SD = 0.5
X_OBS = 1.0


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _abc(seed=7, fused_generations=3, pop=200):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(
        _gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
        population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
        fused_generations=fused_generations,
    )


@pytest.mark.slow
def test_store_sum_stats_false_identical_posterior():
    abc_full = _abc()
    abc_full.new("sqlite://", {"x": X_OBS})
    h_full = abc_full.run(max_nr_populations=5)

    abc_off = _abc()
    abc_off.new("sqlite://", {"x": X_OBS}, store_sum_stats=False)
    h_off = abc_off.run(max_nr_populations=5)

    assert h_off.n_populations == h_full.n_populations
    for t in range(h_full.n_populations):
        df_f, w_f = h_full.get_distribution(m=0, t=t)
        df_o, w_o = h_off.get_distribution(m=0, t=t)
        np.testing.assert_array_equal(df_f["theta"], df_o["theta"])
        np.testing.assert_array_equal(w_f, w_o)
        wd_f = h_full.get_weighted_distances(t)
        wd_o = h_off.get_weighted_distances(t)
        np.testing.assert_array_equal(wd_f["distance"], wd_o["distance"])
    # full run has stats; the off run raises a clear error
    _, stats = h_full.get_weighted_sum_stats(1)
    assert stats.shape[0] == 200
    with pytest.raises(ValueError, match="store_sum_stats"):
        h_off.get_weighted_sum_stats(1)


def test_store_sum_stats_every_k():
    abc = _abc()
    abc.new("sqlite://", {"x": X_OBS}, store_sum_stats=2)
    h = abc.run(max_nr_populations=5)
    assert h.n_populations >= 4
    for t in range(h.n_populations):
        if t % 2 == 0:
            _, stats = h.get_weighted_sum_stats(t)
            assert stats.shape[0] == 200
        else:
            with pytest.raises(ValueError, match="store_sum_stats"):
                h.get_weighted_sum_stats(t)


def test_adopt_device_context_identical_results():
    # donor run with a DIFFERENT seed: its adaptive distance ends fully
    # adapted, and that state must NOT leak into the adopting run (the
    # context is rebound to the adopter's own components)
    donor = _abc(seed=11)
    donor.new("sqlite://", {"x": X_OBS})
    donor.run(max_nr_populations=4)

    ref = _abc(seed=3)
    ref.new("sqlite://", {"x": X_OBS})
    h1 = ref.run(max_nr_populations=4)

    abc2 = _abc(seed=3)
    abc2.new("sqlite://", {"x": X_OBS})
    abc2.adopt_device_context(donor)
    assert abc2._device_ctx._kernels is donor._device_ctx._kernels
    assert abc2._device_ctx.distance is abc2.distance_function
    h2 = abc2.run(max_nr_populations=4)

    assert h2.n_populations == h1.n_populations
    for t in range(h1.n_populations):
        df1, w1 = h1.get_distribution(m=0, t=t)
        df2, w2 = h2.get_distribution(m=0, t=t)
        np.testing.assert_array_equal(df1["theta"], df2["theta"])
        np.testing.assert_array_equal(w1, w2)


def test_adopt_device_context_rejects_different_obs():
    abc1 = _abc(seed=3)
    abc1.new("sqlite://", {"x": X_OBS})
    abc1.run(max_nr_populations=2)
    abc2 = _abc(seed=3)
    abc2.new("sqlite://", {"x": X_OBS + 1.0})
    with pytest.raises(ValueError, match="observed data"):
        abc2.adopt_device_context(abc1)
