"""Statistical integration tests: full ABCSMC vs analytic posteriors.

Mirrors the reference's gold standard (SURVEY.md §4): posterior-vs-analytic
asserts with loose statistical tolerances, not bit-exact asserts
(reference test/base/test_posterior_estimation.py).
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _gauss_jax_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _posterior_moments(history, m=0, par="theta"):
    df, w = history.get_distribution(m)
    mu = float(np.sum(df[par] * w))
    sd = float(np.sqrt(np.sum(w * (df[par] - mu) ** 2)))
    return mu, sd


class TestGaussianToyDevicePath:
    def test_posterior_matches_conjugate(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=400, eps=pt.MedianEpsilon(), seed=1)
        assert abc._device_capable
        assert isinstance(abc.sampler, pt.BatchedSampler)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        mu, sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.15)
        assert sd == pytest.approx(np.sqrt(POST_VAR), abs=0.15)
        # history telemetry recorded per generation
        pops = h.get_all_populations()
        assert len(pops) == h.n_populations + 1  # + PRE_TIME row
        eps_vals = pops[pops.t >= 0]["epsilon"].to_numpy()
        assert np.all(np.diff(eps_vals) < 0)  # shrinking thresholds

    def test_uniform_prior_variant(self):
        prior = pt.Distribution(theta=pt.RV("uniform", -3.0, 6.0))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=400, seed=2)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        mu, sd = _posterior_moments(h)
        # flat prior on [-3,3]: posterior ~ N(x_obs, noise_sd^2) truncated
        assert mu == pytest.approx(X_OBS, abs=0.15)
        assert sd == pytest.approx(NOISE_SD, abs=0.15)


class TestGaussianToyHostPath:
    def test_host_sampler_oracle(self):
        """The scalar host path (reference semantics) on the same toy."""
        rng = np.random.default_rng(0)

        def model(pars):
            return {"x": pars["theta"] + NOISE_SD * rng.normal()}

        prior = pt.Distribution(theta=pt.ScipyRV(
            __import__("scipy.stats", fromlist=["norm"]).norm(0, PRIOR_SD)
        ))
        np.random.seed(0)
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=150,
                        eps=pt.QuantileEpsilon(initial_epsilon=1.0, alpha=0.5),
                        sampler=pt.SingleCoreSampler())
        assert not abc._device_capable
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=4)
        mu, sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.3)
        assert sd == pytest.approx(np.sqrt(POST_VAR), abs=0.25)

    def test_device_and_host_agree(self):
        """Device kernel vs scalar oracle: same posterior within tolerance
        (SURVEY.md §7.3.5 silent-bias guard)."""
        prior_d = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc_d = pt.ABCSMC(_gauss_jax_model(), prior_d, pt.PNormDistance(p=2),
                          population_size=300,
                          eps=pt.ListEpsilon([1.0, 0.5, 0.25]), seed=3)
        abc_d.new("sqlite://", {"x": X_OBS})
        h_d = abc_d.run(max_nr_populations=3)
        mu_d, sd_d = _posterior_moments(h_d)

        rng = np.random.default_rng(5)

        def model(pars):
            return {"x": pars["theta"] + NOISE_SD * rng.normal()}

        import scipy.stats as st

        prior_h = pt.Distribution(theta=pt.ScipyRV(st.norm(0, PRIOR_SD)))
        np.random.seed(5)
        abc_h = pt.ABCSMC(model, prior_h, pt.PNormDistance(p=2),
                          population_size=300,
                          eps=pt.ListEpsilon([1.0, 0.5, 0.25]),
                          sampler=pt.SingleCoreSampler())
        abc_h.new("sqlite://", {"x": X_OBS})
        h_h = abc_h.run(max_nr_populations=3)
        mu_h, sd_h = _posterior_moments(h_h)
        assert mu_d == pytest.approx(mu_h, abs=0.2)
        assert sd_d == pytest.approx(sd_h, abs=0.15)


class TestResume:
    def test_load_and_continue(self, tmp_path):
        db = f"sqlite:///{tmp_path}/resume.db"
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=200, seed=4)
        abc.new(db, {"x": X_OBS})
        h1 = abc.run(max_nr_populations=2)
        assert h1.max_t == 1
        run_id = h1.id

        abc2 = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                         population_size=200, seed=5)
        h2 = abc2.load(db, run_id)
        assert h2.max_t == 1
        h2 = abc2.run(max_nr_populations=4)
        assert h2.max_t == 3
        mu, sd = _posterior_moments(h2)
        assert mu == pytest.approx(POST_MU, abs=0.25)


class TestStoppingRules:
    def test_minimum_epsilon(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=100, seed=6)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(minimum_epsilon=0.8, max_nr_populations=10)
        # MedianEpsilon halves each generation; should stop well before 10
        assert h.n_populations < 6

    def test_max_total_simulations(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=100, seed=7)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=10, max_total_nr_simulations=600)
        assert h.n_populations < 10


class TestTelemetrySurface:
    """Round-1 verdict telemetry asks: jax.profiler hook + storage views."""

    @pytest.mark.slow
    def test_profile_dir_produces_trace(self, tmp_path):
        import os

        import jax

        import pyabc_tpu as pt

        @pt.JaxModel.from_function(["theta"], name="g")
        def model(key, theta):
            return {"x": theta[0] + 0.5 * jax.random.normal(key)}

        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=60,
                        eps=pt.ListEpsilon([1.0, 0.5]), seed=2)
        abc.new("sqlite://", {"x": 1.0})
        trace_dir = str(tmp_path / "trace")
        h = abc.run(max_nr_populations=2, profile_dir=trace_dir)
        assert h.n_populations == 2
        # the profiler writes plugin/... event files under the dir
        found = [
            os.path.join(r, f)
            for r, _, files in os.walk(trace_dir) for f in files
        ]
        assert found, "jax.profiler produced no trace files"

    def test_storage_analysis_views(self):
        import numpy as np

        import jax
        import pyabc_tpu as pt

        @pt.JaxModel.from_function(["theta"], name="g")
        def model(key, theta):
            return {"x": theta[0] + 0.5 * jax.random.normal(key)}

        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=80,
                        eps=pt.ListEpsilon([1.0, 0.5, 0.3]), seed=4)
        abc.new("sqlite://", {"x": 1.0})
        h = abc.run(max_nr_populations=3)
        npp = h.get_nr_particles_per_population()
        assert list(npp.loc[[0, 1, 2]]) == [80, 80, 80]
        ext = h.get_population_extended(h.max_t)
        assert len(ext) == 80 and "w" in ext.columns
        assert h.alive_models(h.max_t) == [0]
        assert h.n_alive_models(h.max_t) == 1
        w, stats = h.get_weighted_sum_stats(h.max_t)
        assert stats.shape[0] == 80 and np.isfinite(stats).all()
