"""Resilience subsystem (round 9): the system survives what it observes.

The fault matrix the ISSUE demands, all deterministic on CPU and `not
slow`: kill-worker-mid-generation (lease requeue + redispatch +
posterior parity vs a fault-free seed-matched run),
broker-blip-during-ship (shared RetryPolicy heals it in place),
duplicate-late-batch (slot-level dedup drops exactly-once),
orchestrator-kill-then-resume-mid-chunk (the fused carry round-trips
bit-exact through the checkpoint and the resumed trajectory is
bit-identical to the uninterrupted run), plus the async History writer's
transient-retry-vs-sticky split and the no-more-TimeoutError graceful
degradation while any worker lives.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.broker.broker import EvalBroker
from pyabc_tpu.broker.protocol import request
from pyabc_tpu.broker.worker import run_worker
from pyabc_tpu.observability import Tracer, VirtualClock
from pyabc_tpu.resilience import (
    CheckpointCorruptError,
    CheckpointManager,
    FaultPlan,
    FaultRule,
    InjectedKill,
    RetryPolicy,
    decode_tree,
    encode_tree,
    install_fault_plan,
    tree_bit_equal,
    uninstall_fault_plan,
)
from pyabc_tpu.resilience.faults import (
    InjectedConnectionError,
    InjectedPersistError,
    InjectedTransientError,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

NOISE_SD = 0.5
X_OBS = 1.0


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    """Every test leaves the process fault-free (the plan is global)."""
    uninstall_fault_plan()
    yield
    uninstall_fault_plan()


# ------------------------------------------------------------- RetryPolicy
def test_retry_policy_backoff_schedule_deterministic():
    p = RetryPolicy(attempts=4, base_s=0.1, max_s=0.3, jitter=0.0)
    assert p.delays() == [0.1, 0.2, 0.3]  # doubled, then capped
    import random

    # jitter bounded and reproducible under a seeded rng
    pj = RetryPolicy(attempts=4, base_s=0.1, max_s=10.0, jitter=0.5)
    d1 = pj.delays(random.Random(7))
    d2 = pj.delays(random.Random(7))
    assert d1 == d2
    for i, d in enumerate(d1):
        nominal = 0.1 * 2 ** i
        assert 0.5 * nominal <= d <= 1.5 * nominal


def test_retry_policy_call_retries_then_raises():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise ConnectionError("down")

    p = RetryPolicy(attempts=3, base_s=0.01, jitter=0.0)
    with pytest.raises(ConnectionError):
        p.call(flaky, sleep=sleeps.append)
    assert calls["n"] == 3
    assert len(sleeps) == 2  # no sleep after the final failure

    # non-retryable exceptions propagate immediately
    calls["n"] = 0

    def bug():
        calls["n"] += 1
        raise ValueError("bug")

    with pytest.raises(ValueError):
        p.call(bug, sleep=sleeps.append)
    assert calls["n"] == 1

    # success after transient failures returns the value
    state = {"n": 0}

    def eventually():
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("blip")
        return "ok"

    assert p.call(eventually, sleep=lambda _s: None) == "ok"


def test_retry_policy_deadline_on_injected_clock():
    clk = VirtualClock(0.0)

    def fail():
        clk.advance(10.0)  # each attempt burns virtual time
        raise ConnectionError("down")

    p = RetryPolicy(attempts=10, base_s=0.01, jitter=0.0)
    calls = []
    with pytest.raises(ConnectionError):
        p.call(fail, clock=clk, deadline_s=15.0,
               sleep=lambda s: calls.append(s))
    # first attempt at t=0 -> retry; second ends at t=20 > deadline 15
    assert len(calls) == 1


# --------------------------------------------------------------- FaultPlan
def test_fault_plan_parse_and_counting():
    plan = FaultPlan.parse(
        "worker.batch:kill:after=2,match=mortal;"
        "protocol.request:drop:max_fires=2;"
        "history.persist:transient:max_fires=none,every=3"
    )
    sites = {r.site: r for r in plan.rules}
    assert sites["worker.batch"].after == 2
    assert sites["worker.batch"].match == "mortal"
    assert sites["protocol.request"].max_fires == 2
    assert sites["history.persist"].max_fires is None
    assert sites["history.persist"].every == 3
    with pytest.raises(ValueError):
        FaultPlan.parse("worker.batch:explode")
    with pytest.raises(ValueError):
        FaultPlan.parse("")


def test_fault_plan_after_every_match_and_max_fires():
    plan = FaultPlan([FaultRule(site="s", kind="kill", after=2, every=2,
                                max_fires=2, match="mortal")])
    fired = []
    for i in range(12):
        try:
            plan.probe("s", worker_id="w-mortal-1")
        except InjectedKill:
            fired.append(i)
    # probes 0,1 skipped (after=2); then every 2nd: fires at probe 2, 4
    assert fired == [2, 4]
    # other sites / unmatched worker ids never fire
    plan2 = FaultPlan([FaultRule(site="s", kind="kill", match="mortal")])
    plan2.probe("other_site", worker_id="w-mortal-1")
    plan2.probe("s", worker_id="w-steady-1")
    assert plan2.n_fired() == 0


def test_fault_plan_probabilistic_rules_are_seeded():
    def run(seed):
        plan = FaultPlan(
            [FaultRule(site="s", kind="kill", p=0.5, max_fires=None)],
            seed=seed,
        )
        out = []
        for i in range(30):
            try:
                plan.probe("s")
                out.append(0)
            except InjectedKill:
                out.append(1)
        return out

    assert run(3) == run(3)  # deterministic replay
    assert run(3) != run(4)  # and actually seed-dependent
    assert 0 < sum(run(3)) < 30


def test_maybe_fault_is_noop_without_plan():
    from pyabc_tpu.resilience import maybe_fault

    maybe_fault("worker.batch", worker_id="w")  # must not raise


# ----------------------------------------- device.mesh topology kinds (r15)
def test_device_fault_kinds_parse_poll_and_count():
    """The mesh-topology kinds are POLLED (the serving scheduler
    applies the loss/cordon) and parse a ``devices=`` range; the
    deterministic counters (after/max_fires) work exactly like every
    other kind, and firings count into faults_injected_total."""
    from pyabc_tpu.observability import global_metrics
    from pyabc_tpu.observability.metrics import FAULTS_INJECTED_TOTAL
    from pyabc_tpu.resilience import (
        install_fault_plan,
        maybe_device_fault,
        uninstall_fault_plan,
    )

    plan = FaultPlan.parse(
        "device.mesh:device_lost:after=1,devices=4-7;"
        "device.mesh:device_degraded:devices=2")
    install_fault_plan(plan)
    try:
        before = global_metrics().counter(
            FAULTS_INJECTED_TOTAL, "faults fired").value
        # first poll: device_lost skipped (after=1), degraded fires
        assert maybe_device_fault() == {
            "kind": "device_degraded", "devices": [2]}
        assert maybe_device_fault() == {
            "kind": "device_lost", "devices": [4, 5, 6, 7]}
        assert maybe_device_fault() is None  # both one-shot by default
        assert plan.n_fired("device.mesh") == 2
        assert global_metrics().counter(
            FAULTS_INJECTED_TOTAL, "faults fired").value == before + 2
    finally:
        uninstall_fault_plan()


def test_device_fault_kinds_need_devices_and_never_probe():
    """A device kind without ``devices=`` is a spec error; probe() and
    poll() never see device rules (class separation keeps mixed plans
    deterministic per site) and maybe_device_fault is a no-op without a
    plan."""
    from pyabc_tpu.resilience import maybe_device_fault

    with pytest.raises(ValueError):
        FaultRule(site="device.mesh", kind="device_lost")
    with pytest.raises(ValueError):
        FaultPlan.parse("device.mesh:device_lost:devices=7-4")
    assert maybe_device_fault() is None  # no plan installed
    plan = FaultPlan([
        FaultRule(site="device.mesh", kind="device_lost", devices="0"),
        FaultRule(site="device.mesh", kind="kill"),
    ])
    # probe consumes only the raise-class rule; the device rule's
    # counters are untouched by it
    with pytest.raises(InjectedKill):
        plan.probe("device.mesh")
    assert plan.poll("device.mesh") is None  # corruption class: none here
    ev = plan.poll_device("device.mesh")
    assert ev == {"kind": "device_lost", "devices": [0]}


# ------------------------------------------------- protocol.request retry
def test_request_retries_through_injected_drops():
    broker = EvalBroker("127.0.0.1", 0)
    try:
        install_fault_plan(FaultPlan([
            FaultRule(site="protocol.request", kind="drop", max_fires=2),
        ]))
        # the first two connect attempts drop; the shared RetryPolicy
        # (3 attempts) heals the blip in place
        kind, status = request(broker.address, ("status",))
        assert kind == "status"
        assert status.done
    finally:
        uninstall_fault_plan()
        broker.stop()


def test_request_exhausted_retries_raise():
    broker = EvalBroker("127.0.0.1", 0)
    try:
        install_fault_plan(FaultPlan([
            FaultRule(site="protocol.request", kind="drop",
                      max_fires=None),
        ]))
        with pytest.raises(ConnectionError):
            request(broker.address, ("status",),
                    retry=RetryPolicy(attempts=2, base_s=0.001))
    finally:
        uninstall_fault_plan()
        broker.stop()


# ----------------------------------------------------- leases + dedup
def test_lease_expiry_requeues_to_live_worker_and_dedups():
    clk = VirtualClock(0.0)
    broker = EvalBroker("127.0.0.1", 0, clock=clk, liveness_s=5.0,
                        lease_timeout_s=3.0)
    try:
        broker.start_generation(0, b"x", 8, batch=10, wait_for_all=True)
        gen = broker._gen
        _, a0, a1 = broker._dispatch(("get_slots", "A", gen, 10))
        assert (a0, a1) == (0, 10)
        # A delivers 3 (2 accepted), then goes silent mid-batch
        assert broker._dispatch(("results", "A", gen, [
            (0, b"p", True), (1, b"p", True), (2, b"p", False),
        ])) == ("ok",)
        # before expiry nothing is requeued: B gets fresh slots
        clk.advance(1.0)
        _, b0, b1 = broker._dispatch(("get_slots", "B", gen, 5))
        assert b0 == 10
        # past A's lease deadline (B's contact refreshed only B's lease)
        clk.advance(6.0)
        _, r0, r1 = broker._dispatch(("get_slots", "B", gen, 10))
        assert (r0, r1) == (3, 10), "A's undelivered slots redispatch"
        st = broker.status()
        assert st.leases["redispatched_total"] == 1
        assert st.leases["leases_expired"] >= 1
        # B finishes the redispatched batch...
        assert broker._dispatch(("results", "B", gen, [
            (s, b"q", s in (3, 4)) for s in range(3, 10)
        ])) == ("ok",)
        # ...and A limps back with the SAME batch: every slot is a late
        # duplicate and must be dropped exactly-once (no double count)
        n_acc_before = broker.status().n_acc
        assert broker._dispatch(("results", "A", gen, [
            (s, b"p", s in (3, 4)) for s in range(3, 10)
        ])) == ("ok",)
        st = broker.status()
        assert st.n_acc == n_acc_before, "duplicate batch double-counted"
        assert st.leases["duplicates_dropped"] == 7
        # delivered slots are unique (exactly-once)
        slots = [s for s, _b, _a in broker.results_snapshot()[0]]
        assert len(slots) == len(set(slots))
        assert any(ev.get("action") == "dedup_drop" for ev in st.recovery)
        # recovery spans cover the orphaned window on the broker clock
        spans = broker.drain_recovery_spans()
        redis = [sp for sp in spans
                 if sp["name"] == "recovery.redispatch"]
        assert redis and redis[0]["end"] > redis[0]["start"]
    finally:
        broker.stop()


def test_presumed_dead_worker_requeues_before_lease_timeout():
    clk = VirtualClock(0.0)
    broker = EvalBroker("127.0.0.1", 0, clock=clk, liveness_s=2.0,
                        lease_timeout_s=60.0)
    try:
        broker.start_generation(0, b"x", 5, batch=5, wait_for_all=True)
        gen = broker._gen
        broker._dispatch(("get_slots", "A", gen, 5))
        clk.advance(3.0)  # A silent past the LIVENESS window only
        _, r0, r1 = broker._dispatch(("get_slots", "B", gen, 5))
        assert (r0, r1) == (0, 5), "presumed-dead requeue must not wait " \
                                   "for the 60s lease timeout"
    finally:
        broker.stop()


def test_static_mode_dedup_drops_second_accept_only():
    clk = VirtualClock(0.0)
    broker = EvalBroker("127.0.0.1", 0, clock=clk, lease_timeout_s=1.0)
    try:
        broker.start_generation(0, b"x", 2, batch=2, mode="static")
        gen = broker._gen
        broker._dispatch(("get_slots", "A", gen, 2))
        clk.advance(2.0)
        broker._dispatch(("get_slots", "B", gen, 2))  # requeued to B
        # both deliver unit 0: rejects are records (kept), the second
        # ACCEPT for the same quota unit is the duplicate
        assert broker._dispatch(("results", "A", gen, [
            (0, b"r", False), (0, b"p", True),
        ])) == ("ok",)
        broker._dispatch(("results", "B", gen, [
            (0, b"r", False), (0, b"q", True),
        ]))
        st = broker.status()
        assert st.n_acc == 1
        assert st.leases["duplicates_dropped"] == 1
    finally:
        broker.stop()


# ------------------------------------------ fault matrix: worker kills
def _spawn_worker(port, worker_id=None, fault_plan=None, seed=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    if seed is not None:
        env["PYABC_TPU_WORKER_SEED"] = str(seed)
    code = (
        "from pyabc_tpu.broker import run_worker; import sys; "
        "run_worker('127.0.0.1', int(sys.argv[1]), "
        "worker_id=sys.argv[2] or None, "
        "fault_plan=(sys.argv[3] or None))"
    )
    return subprocess.Popen(
        [sys.executable, "-c", code, str(port), worker_id or "",
         fault_plan or ""],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _elastic_abc(sampler, pop=50, seed=4, delay_s=0.004):
    def sim(pars):
        if delay_s:
            time.sleep(delay_s)
        return {"x": pars["theta"] + NOISE_SD * np.random.normal()}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(pt.SimpleModel(sim, name="gauss_host"), prior,
                     pt.PNormDistance(p=2), population_size=pop,
                     eps=pt.QuantileEpsilon(initial_epsilon=1.5,
                                            alpha=0.5),
                     sampler=sampler, seed=seed)


def test_worker_killed_every_generation_self_heals():
    """The headline fault-matrix case: one worker hard-killed mid-batch
    (no bye, slots leased) in every generation of a wait_for_all run —
    pre-round-9 this stalled until generation_timeout; now the leases
    requeue, the survivor finishes, >= 1 batch redispatches, nothing
    double-counts, and the posterior matches a fault-free seed-matched
    run within the existing parity tolerances."""
    gens = 3
    results = {}
    for faulty in (True, False):
        tracer = Tracer()
        sampler = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                                    generation_timeout=20.0,
                                    wait_for_all_samples=True,
                                    lease_timeout_s=1.0)
        port = sampler.address[1]
        workers = [_spawn_worker(port, worker_id="steady", seed=7)]
        live = {"on": True}
        respawns = {"n": 0}

        def babysit(port=port, live=live, respawns=respawns):
            # a fresh mortal worker per life, killed after its 2nd
            # batch each life -> at least one kill per generation
            life = 0
            proc = _spawn_worker(
                port, worker_id=f"mortal-{life}", seed=13 + life,
                fault_plan="worker.batch:kill:after=1,max_fires=1",
            )
            while live["on"]:
                if proc.poll() is not None:
                    life += 1
                    respawns["n"] += 1
                    proc = _spawn_worker(
                        port, worker_id=f"mortal-{life}", seed=13 + life,
                        fault_plan="worker.batch:kill:after=1,max_fires=1",
                    )
                time.sleep(0.1)
            proc.kill()

        th = None
        if faulty:
            th = threading.Thread(target=babysit, daemon=True)
            th.start()
        try:
            abc = _elastic_abc(sampler, pop=50, seed=4)
            abc.tracer = tracer
            abc.new("sqlite://", {"x": X_OBS})
            h = abc.run(max_nr_populations=gens)  # must NOT TimeoutError
            assert h.n_populations == gens
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            status = sampler.broker.status()
            results[faulty] = (mu, status, respawns["n"],
                               [sp for sp in tracer.spans()
                                if sp.name.startswith("recovery.")])
        finally:
            live["on"] = False
            if th is not None:
                th.join(timeout=5)
            for p in workers:
                p.kill()
            sampler.stop()
    mu_fault, status, kills, rec_spans = results[True]
    mu_clean, status_clean, _, _ = results[False]
    assert kills >= 1, "no worker was ever killed"
    # the self-healing evidence: the dead workers' leased batches were
    # redispatched (the acceptance criterion's metric)
    assert status.leases["redispatched_total"] >= 1, status.leases
    # no batch double-counted: dedup accounting is exact
    assert status.leases["duplicates_dropped"] >= 0
    assert status_clean.leases["redispatched_total"] == 0
    # posterior parity within the existing elastic-test tolerances
    # (conjugate posterior mean 0.8; per-run spread calibrated in
    # tests/test_elastic.py round 6)
    assert mu_fault == pytest.approx(0.8, abs=0.55)
    assert mu_clean == pytest.approx(0.8, abs=0.55)
    assert mu_fault == pytest.approx(mu_clean, abs=0.7)


def test_generation_timeout_degrades_gracefully_while_workers_live():
    """A too-short generation_timeout must NOT kill a run whose workers
    are alive but slow: the deadline extends (counted + spanned) and the
    run completes on the survivors."""
    tracer = Tracer()
    sampler = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                                generation_timeout=0.5)
    port = sampler.address[1]
    worker = _spawn_worker(port, worker_id="slowpoke", seed=7)
    try:
        # wait out the worker's interpreter/jax startup: the graceful
        # path is "live but SLOW workers", not "nobody ever joined"
        # (the latter still raises, see the test below)
        deadline = time.time() + 60
        while time.time() < deadline \
                and not sampler.broker.status().workers:
            time.sleep(0.1)
        assert sampler.broker.status().workers, "worker never joined"
        abc = _elastic_abc(sampler, pop=30, seed=4, delay_s=0.01)
        abc.tracer = tracer
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=1)  # >> 0.5s of simulate time
        assert h.n_populations == 1
        ext = [sp for sp in tracer.spans()
               if sp.name == "recovery.timeout_extended"]
        assert ext, "deadline was never extended"
    finally:
        worker.kill()
        sampler.stop()


def test_generation_timeout_still_raises_with_no_live_workers():
    sampler = pt.ElasticSampler(host="127.0.0.1", port=0,
                                generation_timeout=0.3)
    try:
        abc = _elastic_abc(sampler, pop=10, seed=4)
        abc.new("sqlite://", {"x": X_OBS})
        with pytest.raises(TimeoutError):
            abc.run(max_nr_populations=1)
    finally:
        sampler.stop()


# ----------------------------------------- History writer transient retry
def _tiny_population(n=5):
    from pyabc_tpu.core.parameters import ParameterSpace
    from pyabc_tpu.core.population import Population
    from pyabc_tpu.core.sumstat_spec import SumStatSpec

    spec = SumStatSpec({"x": np.array([1.0])})
    return Population(
        ms=np.zeros(n, np.int32),
        thetas=np.linspace(0.0, 1.0, n)[:, None],
        weights=np.full(n, 1.0 / n),
        distances=np.full(n, 0.1),
        sumstats=np.ones((n, 1), np.float32),
        spaces=[ParameterSpace(["theta"])], sumstat_spec=spec,
        model_names=["m0"],
    )


def _history_with_run():
    h = pt.History("sqlite://")
    h.store_initial_data(None, {}, {"x": np.array([1.0])}, {}, ["m0"],
                         "{}", "{}", "{}")
    return h


def test_async_writer_retries_transient_persist_failures():
    """Regression for the sticky-death bug: two transient failures (db
    locked / injected) then success must NOT latch the writer — the
    population persists and later appends keep working."""
    h = _history_with_run()
    install_fault_plan(FaultPlan([
        FaultRule(site="history.persist", kind="transient", max_fires=2),
    ]))
    h.start_async_writer()
    pop = _tiny_population()
    h.append_population_async(0, 1.0, pop, 5, ["m0"])
    h.flush()  # would raise pre-round-9
    uninstall_fault_plan()
    h.append_population_async(1, 0.5, pop, 5, ["m0"])
    h.done()
    assert h.n_populations == 2


def test_async_writer_stays_sticky_for_permanent_failures():
    """The sticky semantics survive for genuinely broken db state: a
    non-transient error latches the writer, queued work drains without
    executing, and every later submit/flush re-raises."""
    h = _history_with_run()
    install_fault_plan(FaultPlan([
        FaultRule(site="history.persist", kind="error", max_fires=None),
    ]))
    h.start_async_writer()
    pop = _tiny_population()
    h.append_population_async(0, 1.0, pop, 5, ["m0"])
    with pytest.raises(InjectedPersistError):
        h.flush()
    with pytest.raises(InjectedPersistError):
        h.append_population_async(1, 0.5, pop, 5, ["m0"])
    uninstall_fault_plan()
    # still sticky after the plan is gone: the latch is the writer's
    with pytest.raises(InjectedPersistError):
        h.flush()
    assert h.n_populations == 0


def test_async_writer_transient_exhaustion_latches_sticky():
    h = _history_with_run()
    install_fault_plan(FaultPlan([
        FaultRule(site="history.persist", kind="transient",
                  max_fires=None),
    ]))
    h.start_async_writer()
    h.append_population_async(0, 1.0, _tiny_population(), 5, ["m0"])
    with pytest.raises(InjectedTransientError):
        h.flush()


def test_history_prune_from(tmp_path, store_scheme):
    # both backends (round 17): the columnar leg must delete generation
    # FILES together with the metadata rows
    h = pt.History(f"{store_scheme}:///{tmp_path}/prune.db")
    h.store_initial_data(None, {}, {"x": np.array([1.0])}, {}, ["m0"],
                         "{}", "{}", "{}")
    pop = _tiny_population()
    for t in range(3):
        h.append_population(t, 1.0 - 0.2 * t, pop, 5, ["m0"])
    assert h.max_t == 2
    assert h.prune_from(1) == 2
    assert h.max_t == 0
    df, w = h.get_distribution(0, 0)  # survivors intact
    assert len(df) == 5
    assert h.prune_from(5) == 0
    if h.columnar:
        assert [p.name for p in
                h._colstore.run_dir(h.id).glob("*.parquet")] \
            == ["t0.parquet"]


# -------------------------------------------------- checkpoint round-trip
def test_checkpoint_tree_roundtrip_bit_exact(tmp_path):
    import jax

    tree = (
        ({"mu": np.arange(12, dtype=np.float32).reshape(3, 4),
          "chol": np.eye(3, dtype=np.float32)},),
        np.asarray(jax.random.key_data(jax.random.key(5))),
        (np.float32(1.5), np.zeros((), np.int32), np.array(True)),
        [np.array([1, 2, 3], np.int64), None, "tag", 7, 2.5, False],
    )
    assert tree_bit_equal(decode_tree(encode_tree(tree)), tree_like(tree))

    mgr = CheckpointManager(str(tmp_path / "ck.bin"))
    mgr.save({"kind": "fused_carry", "t": 3, "carry": tree})
    loaded = mgr.load()
    assert loaded["t"] == 3
    assert tree_bit_equal(loaded["carry"], tree_like(tree))
    mgr.clear()
    assert mgr.load() is None


def tree_like(tree):
    """The canonical post-roundtrip form: array-like leaves become
    numpy arrays (scalars/str/bool/None pass through)."""
    if tree is None or isinstance(tree, (bool, int, float, str, bytes)):
        return tree
    if isinstance(tree, dict):
        return {k: tree_like(v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(tree_like(v) for v in tree)
    if isinstance(tree, list):
        return [tree_like(v) for v in tree]
    return np.asarray(tree)


def _saved_checkpoint(tmp_path):
    """A real saved checkpoint + its manager (integrity-test fixture)."""
    path = str(tmp_path / "ck.bin")
    mgr = CheckpointManager(path)
    mgr.save({"kind": "fused_carry", "t": 3, "abc_id": 1,
              "carry": ({"thetas":
                         np.arange(8, dtype=np.float32).reshape(2, 4)},)})
    return mgr, path


def test_checkpoint_corruption_raises_typed_error(tmp_path):
    """A non-checkpoint file raises CheckpointCorruptError naming the
    failure (bad magic), never an opaque unpickling crash."""
    path = tmp_path / "ck.bin"
    path.write_bytes(b"not a checkpoint at all, but long enough........")
    with pytest.raises(CheckpointCorruptError, match="bad magic"):
        CheckpointManager(str(path)).load()
    # missing file is NOT corruption: plain None (fresh run)
    assert CheckpointManager(str(tmp_path / "absent.bin")).load() is None


def test_checkpoint_bit_flip_detected(tmp_path):
    """Flipping ONE payload bit of a real checkpoint fails the CRC."""
    mgr, path = _saved_checkpoint(tmp_path)
    assert mgr.load()["t"] == 3  # sanity: intact file loads
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x10  # flip a bit mid-payload
    open(path, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        mgr.load()


def test_checkpoint_truncation_detected(tmp_path):
    """A truncated checkpoint (torn copy, full disk) is length-checked
    before any parse; truncating into the header is also typed."""
    mgr, path = _saved_checkpoint(tmp_path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) - 7])
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        mgr.load()
    open(path, "wb").write(raw[:10])  # shorter than the header itself
    with pytest.raises(CheckpointCorruptError, match="too short"):
        mgr.load()


def test_checkpoint_version_mismatch_detected(tmp_path):
    """A future/past schema version is rejected loudly (the header is
    checked before the payload is trusted)."""
    import struct

    mgr, path = _saved_checkpoint(tmp_path)
    raw = bytearray(open(path, "rb").read())
    raw[4:8] = struct.pack("<I", 9999)
    open(path, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptError, match="schema version"):
        mgr.load()


def test_corrupt_checkpoint_falls_back_to_history_resume(
        tmp_path, store_scheme):
    """End-to-end: a bit-flipped checkpoint does not block resume — the
    run falls back to generation-granularity History replay (the
    epsilon-trail path) and completes. Both backends: the columnar leg
    replays the trail out of the Parquet generations."""
    db = f"{store_scheme}:///{tmp_path}/run.db"
    ck = str(tmp_path / "carry.ck")
    abc1 = _fused_abc(ck)
    abc1.new(db, {"x": X_OBS})
    install_fault_plan(FaultPlan([
        FaultRule(site="orchestrator.chunk", kind="kill", after=1,
                  max_fires=1),
    ]))
    with pytest.raises(InjectedKill):
        abc1.run(max_nr_populations=8)
    uninstall_fault_plan()
    raw = bytearray(open(ck, "rb").read())
    raw[-5] ^= 0x01
    open(ck, "wb").write(bytes(raw))
    abc2 = _fused_abc(ck)
    abc2.load(db, abc1.history.id)
    h2 = abc2.run(max_nr_populations=8)
    assert abc2.resumed_from_checkpoint_t is None  # fell back
    assert h2.n_populations == 8


# -------------------------- orchestrator kill + mid-chunk resume (fused)
def _gauss_jax_model():
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _fused_abc(ckpath, seed=11, pop=200, G=4):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                     population_size=pop, eps=pt.MedianEpsilon(),
                     seed=seed, fused_generations=G,
                     checkpoint_path=ckpath)


def test_orchestrator_kill_then_resume_mid_chunk(tmp_path, store_scheme):
    """The acceptance criterion: kill the orchestrator between chunks,
    resume from the checkpoint, and the fused-loop carry (RNG key data,
    fitted-proposal state, epsilon trail, refit counter) round-trips
    BIT-EXACT — proven end-to-end by the resumed run's populations being
    bit-identical to an uninterrupted seed-matched run, which
    generation-granularity History resume (host refit replay + RNG
    restart) cannot produce.

    Parameterized over BOTH History backends (round 17): the
    db-at-or-ahead-of-checkpoint ordering and the prune-before-rerun
    seam must hold identically when generations land as columnar
    Parquet batches — and the interrupted columnar run must end
    bit-identical to the clean ROW-store reference (cross-store
    parity)."""
    db_i = f"{store_scheme}:///{tmp_path}/interrupted.db"
    db_c = f"sqlite:///{tmp_path}/clean.db"
    ck = str(tmp_path / "carry.ck")
    gens = 8

    # uninterrupted reference
    abc_ref = _fused_abc(None)
    abc_ref.new(db_c, {"x": X_OBS})
    h_ref = abc_ref.run(max_nr_populations=gens)
    assert h_ref.n_populations == gens

    # interrupted run: the injected kill lands while chunk 2 (t=4..7) is
    # being processed — after its dispatch, before its persist
    abc1 = _fused_abc(ck)
    abc1.new(db_i, {"x": X_OBS})
    install_fault_plan(FaultPlan([
        FaultRule(site="orchestrator.chunk", kind="kill", after=1,
                  max_fires=1),
    ]))
    with pytest.raises(InjectedKill):
        abc1.run(max_nr_populations=gens)
    uninstall_fault_plan()
    assert os.path.exists(ck), "no checkpoint was written"

    # the checkpoint itself round-trips bit-exact (direct assertion on
    # the carry payload, independent of the end-to-end equality below)
    mgr = CheckpointManager(ck)
    saved = mgr.load()
    assert saved is not None and saved["kind"] == "fused_carry"
    assert saved["t"] == 4  # one full chunk (G=4) was processed
    assert tree_bit_equal(decode_tree(encode_tree(saved["carry"])),
                          saved["carry"])

    # resume in a FRESH orchestrator (no shared state with abc1)
    abc2 = _fused_abc(ck)
    abc2.load(db_i, abc1.history.id)
    h2 = abc2.run(max_nr_populations=gens)
    assert abc2.resumed_from_checkpoint_t == 4, \
        "resume must adopt the mid-chunk checkpoint, not replay History"
    assert h2.n_populations == gens

    # bit-identical trajectory: every post-resume generation equals the
    # uninterrupted run's (same thetas, weights, epsilons — exactly)
    eps_ref = h_ref.get_all_populations().query("t >= 0")["epsilon"]
    eps_res = h2.get_all_populations().query("t >= 0")["epsilon"]
    assert np.array_equal(eps_ref.to_numpy(), eps_res.to_numpy())
    for t in range(gens):
        df_r, w_r = h_ref.get_distribution(0, t)
        df_2, w_2 = h2.get_distribution(0, t)
        assert np.array_equal(np.sort(df_r["theta"].to_numpy()),
                              np.sort(df_2["theta"].to_numpy())), t
        assert np.array_equal(np.sort(w_r), np.sort(w_2)), t
    # each generation persisted exactly once (prune prevented doubles)
    pops = h2.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(pops) == sorted(set(pops)) == list(range(gens))
    # a cleanly finished run deletes its checkpoint
    assert not os.path.exists(ck)


def test_checkpoint_ignored_for_mismatched_config(tmp_path):
    """A checkpoint from a different run id / config must be ignored
    (generation-granularity resume still works; no crash)."""
    db = f"sqlite:///{tmp_path}/run.db"
    ck = str(tmp_path / "carry.ck")
    abc1 = _fused_abc(ck, seed=11)
    abc1.new(db, {"x": X_OBS})
    install_fault_plan(FaultPlan([
        FaultRule(site="orchestrator.chunk", kind="kill", after=1,
                  max_fires=1),
    ]))
    with pytest.raises(InjectedKill):
        abc1.run(max_nr_populations=8)
    uninstall_fault_plan()
    # resume with a DIFFERENT seed: fingerprint mismatch -> no adoption
    abc2 = _fused_abc(ck, seed=12)
    abc2.load(db, abc1.history.id)
    h2 = abc2.run(max_nr_populations=8)
    assert abc2.resumed_from_checkpoint_t is None
    assert h2.n_populations == 8


# ---------------------------------------------------- device-context reset
def test_device_reset_self_heals(tmp_path):
    """An injected device-context reset mid-run drops the compiled
    kernels; the orchestrator rebuilds and the run completes."""
    abc = _fused_abc(None, seed=3, pop=100, G=2)
    abc.new("sqlite://", {"x": X_OBS})
    # fire once, after the first context build
    install_fault_plan(FaultPlan([
        FaultRule(site="device.context", kind="reset", after=1,
                  max_fires=1),
    ]))
    try:
        h = abc.run(max_nr_populations=4)
    finally:
        uninstall_fault_plan()
    assert h.n_populations == 4


# ------------------------------------- lease state machine (property-style)
def _lease_invariants(table, granted, delivered, requeued_expect=None):
    """The two invariants the broker's healing rests on:

    - EXACTLY-ONCE: a dynamic slot is admitted at most once, ever;
    - NO LOST SLOT: every granted-but-undelivered slot is either still
      owned by an outstanding lease or waiting in the requeue — nothing
      falls on the floor, no matter the interleaving.
    """
    st = table.stats()
    outstanding = set(table._slot_owner)
    queued = set()
    for a, b, _ts in table._requeue:
        queued.update(range(a, b))
    # a slot can never be both owned and requeued
    assert not (outstanding & queued)
    lost = granted - delivered - outstanding - queued
    assert not lost, f"slots lost by the lease table: {sorted(lost)[:10]}"
    assert st["outstanding_slots"] == len(outstanding)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
def test_lease_table_randomized_event_sequences(seed):
    """Property-style: drive the LeaseTable through a long seeded random
    sequence of grant / deliver / duplicate-deliver / worker-touch /
    clock-advance / reap / dead-worker-reap / redispatch events and
    assert the exactly-once and no-lost-slot invariants after EVERY
    event. Each seed is a different interleaving; the rng is seeded so a
    failure replays deterministically."""
    import random as _random

    from pyabc_tpu.resilience.lease import LeaseTable

    rng = _random.Random(seed)
    clk = VirtualClock(0.0)
    table = LeaseTable(clk, timeout_s=5.0)
    workers = [f"w{i}" for i in range(4)]
    next_slot = 0
    granted: set[int] = set()
    delivered: set[int] = set()
    admitted: list[int] = []

    for _step in range(400):
        op = rng.choices(
            ["grant", "deliver", "dup", "touch", "advance", "reap",
             "dead", "redispatch"],
            weights=[4, 6, 2, 2, 3, 2, 1, 3],
        )[0]
        if op == "grant":
            k = rng.randint(1, 8)
            table.grant(rng.choice(workers), next_slot, next_slot + k)
            granted.update(range(next_slot, next_slot + k))
            next_slot += k
        elif op == "deliver" and table._slot_owner:
            slot = rng.choice(list(table._slot_owner))
            wid = table._leases[table._slot_owner[slot]]["wid"]
            table.touch_worker(wid)
            if table.admit(slot, accepted=True, mode="dynamic"):
                admitted.append(slot)
                delivered.add(slot)
            table.note_delivery(slot)
        elif op == "dup" and delivered:
            # a late duplicate of an ALREADY-delivered slot must drop
            slot = rng.choice(sorted(delivered))
            assert not table.admit(slot, accepted=rng.random() < 0.5,
                                   mode="dynamic")
        elif op == "touch":
            table.touch_worker(rng.choice(workers))
        elif op == "advance":
            clk.advance(rng.uniform(0.0, 4.0))
        elif op == "reap":
            table.reap(clk.now())
        elif op == "dead":
            table.reap(clk.now(), dead_wids=[rng.choice(workers)])
        elif op == "redispatch":
            taken = table.take_requeued(rng.choice(workers),
                                        rng.randint(1, 6))
            if taken is not None:
                a, b, ts = taken
                assert a < b and ts <= clk.now()
        _lease_invariants(table, granted, delivered)

    # exactly-once held across the whole history
    assert len(admitted) == len(set(admitted))
    # drain everything still outstanding/requeued through deliveries and
    # redispatches: the table must converge to empty with every granted
    # slot delivered exactly once
    for _drain in range(10000):
        if table._slot_owner:
            slot = rng.choice(list(table._slot_owner))
            if table.admit(slot, accepted=True, mode="dynamic"):
                delivered.add(slot)
            table.note_delivery(slot)
        elif table._requeue:
            table.take_requeued(rng.choice(workers), 8)
        else:
            break
        _lease_invariants(table, granted, delivered)
    assert granted == delivered
    assert not table._slot_owner and not table._requeue
