"""Elastic worker pool (the Redis-sampler analog) — join/leave/die freely.

The reference's signature execution capability (SURVEY.md §2.3 Redis row,
§5.3): workers connect to a broker at any time, a worker SIGKILLed
mid-generation costs nothing but throughput, and a late joiner picks up
the current generation. Exercised here with REAL worker subprocesses
against the in-process TCP broker.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.broker.protocol import request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORKER_CODE = (
    "from pyabc_tpu.broker import run_worker; "
    "import sys; run_worker('127.0.0.1', int(sys.argv[1]))"
)

NOISE_SD = 0.5
X_OBS = 1.0


def _host_model(delay_s: float = 0.0):
    def sim(pars):
        if delay_s:
            time.sleep(delay_s)
        return {"x": pars["theta"] + NOISE_SD * np.random.normal()}

    return pt.SimpleModel(sim, name="gauss_host")


def _abc(sampler, delay_s=0.0, pop=80):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(_host_model(delay_s), prior, pt.PNormDistance(p=2),
                     population_size=pop,
                     eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
                     sampler=sampler, seed=4)


def _spawn_worker(port):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-c", WORKER_CODE, str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


@pytest.fixture
def sampler():
    s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                          generation_timeout=240.0)
    yield s
    s.stop()


def _throttle_persist(abc, delay_s: float = 0.3):
    """Slow the orchestrator's persist step so look-ahead workers have a
    GUARANTEED window to deliver pre-published next-generation results
    before the orchestrator adopts — head-start assertions then test the
    overlap MECHANISM instead of incidental scheduler timing (the
    round-5 full-suite-load flake)."""
    orig = abc.history.append_population

    def slow_append(*a, **k):
        time.sleep(delay_s)
        return orig(*a, **k)

    abc.history.append_population = slow_append


def test_posterior_with_two_workers(sampler):
    port = sampler.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    try:
        abc = _abc(sampler)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations == 3
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        # conjugate posterior mean 0.8 (prior N(0,1), noise sd 0.5)
        assert mu == pytest.approx(0.8, abs=0.35)
        # both workers contributed
        kind, status = request(("127.0.0.1", port), ("status",))
        assert kind == "status"
        contributing = [w_ for w_, info in status.workers.items()
                        if info.get("n_results", 0) > 0]
        assert len(contributing) == 2
    finally:
        for p in workers:
            p.kill()


@pytest.mark.slow
def test_worker_killed_mid_generation_costs_only_throughput(sampler):
    port = sampler.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    killed = {}

    def killer():
        # let the generation get going, then SIGKILL one worker cold
        time.sleep(1.5)
        workers[0].send_signal(signal.SIGKILL)
        killed["at"] = time.time()

    th = threading.Thread(target=killer)
    try:
        abc = _abc(sampler, delay_s=0.01, pop=60)
        abc.new("sqlite://", {"x": X_OBS})
        th.start()
        h = abc.run(max_nr_populations=2)  # ~2.4k evals x 10ms / workers
        assert h.n_populations == 2, "run must complete despite the kill"
        assert "at" in killed
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(0.8, abs=0.45)
    finally:
        th.join()
        for p in workers:
            p.kill()


@pytest.mark.slow
def test_late_joining_worker_picks_up_current_generation(sampler):
    port = sampler.address[1]
    late = {}
    workers = [_spawn_worker(port)]

    def joiner():
        time.sleep(1.0)
        workers.append(_spawn_worker(port))
        late["at"] = time.time()

    th = threading.Thread(target=joiner)
    try:
        abc = _abc(sampler, delay_s=0.01, pop=60)
        abc.new("sqlite://", {"x": X_OBS})
        th.start()
        h = abc.run(max_nr_populations=2)
        assert h.n_populations == 2
        assert "at" in late
        kind, status = request(("127.0.0.1", port), ("status",))
        contributing = [w_ for w_, info in status.workers.items()
                       if info.get("n_results", 0) > 0]
        assert len(contributing) == 2, "late joiner must have contributed"
    finally:
        th.join()
        for p in workers:
            p.kill()


def test_manager_status_roundtrip(sampler):
    port = sampler.address[1]
    kind, status = request(("127.0.0.1", port), ("status",))
    assert kind == "status"
    assert status.done
    assert status.n_target == 0


def test_wait_for_all_samples_gathers_in_flight():
    """With wait_for_all, the broker must NOT finalize when the acceptance
    target is met while other workers still hold handed-out slots — every
    in-flight evaluation is collected first, so adaptive components see
    the complete, unbiased record set (reference wait_for_all_samples)."""
    from pyabc_tpu.broker.broker import EvalBroker

    broker = EvalBroker("127.0.0.1", 0)
    try:
        broker.start_generation(0, b"x", 2, batch=5, wait_for_all=True)
        gen = broker._gen
        _, a0, a1 = broker._dispatch(("get_slots", "A", gen, 5))
        _, b0, b1 = broker._dispatch(("get_slots", "B", gen, 5))
        assert (a1 - a0) == (b1 - b0) == 5
        # A posts 3 results incl. 2 acceptances: target met, but B's 5
        # slots are in flight -> the generation must stay open, draining
        reply = broker._dispatch(("results", "A", gen, [
            (a0, b"p", True), (a0 + 1, b"p", True), (a0 + 2, b"p", False),
        ]))
        assert reply == ("ok",)
        assert not broker.status().done
        # draining: no new slots are handed out
        assert broker._dispatch(("get_slots", "C", gen, 5)) == ("done",)
        # B delivers its batch -> still 2 of A's slots outstanding
        reply = broker._dispatch(("results", "B", gen, [
            (s, b"p", False) for s in range(b0, b1)
        ]))
        assert reply == ("ok",)
        assert not broker.status().done
        # A delivers the stragglers -> NOW the generation finalizes
        reply = broker._dispatch(("results", "A", gen, [
            (a0 + 3, b"p", False), (a0 + 4, b"p", False),
        ]))
        assert reply == ("done",)
        triples = broker.wait(timeout=5.0)
        assert len(triples) == 10  # every handed-out slot delivered
    finally:
        broker.stop()


def test_without_wait_for_all_finishes_at_target():
    from pyabc_tpu.broker.broker import EvalBroker

    broker = EvalBroker("127.0.0.1", 0)
    try:
        broker.start_generation(0, b"x", 2, batch=5, wait_for_all=False)
        gen = broker._gen
        broker._dispatch(("get_slots", "A", gen, 5))
        broker._dispatch(("get_slots", "B", gen, 5))
        reply = broker._dispatch(("results", "A", gen, [
            (0, b"p", True), (1, b"p", True),
        ]))
        assert reply == ("done",)  # finalized with B's slots abandoned
        assert broker.status().done
    finally:
        broker.stop()


@pytest.mark.slow
def test_sigterm_drains_cleanly_and_deregisters(sampler):
    """kill -TERM mid-generation: the worker ships its current batch,
    deregisters from the broker (no ghost in manager status), and exits
    with code 0 — reference KillHandler semantics."""
    port = sampler.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    terminated = {}

    def terminator():
        # wait until both workers have REGISTERED (the signal handler
        # installs at run_worker entry; a TERM during the slow jax import
        # would hit the default handler and exit -15)
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                _, status = request(("127.0.0.1", port), ("status",))
                if len(status.workers) >= 2:
                    break
            except (ConnectionError, OSError):
                pass
            time.sleep(0.2)
        time.sleep(0.5)  # let a generation batch get going
        workers[0].send_signal(signal.SIGTERM)
        terminated["at"] = time.time()

    th = threading.Thread(target=terminator)
    try:
        abc = _abc(sampler, delay_s=0.01, pop=60)
        abc.new("sqlite://", {"x": X_OBS})
        th.start()
        h = abc.run(max_nr_populations=2)
        assert h.n_populations == 2
        assert "at" in terminated
        assert workers[0].wait(timeout=30) == 0, "graceful exit code"
        kind, status = request(("127.0.0.1", port), ("status",))
        assert kind == "status"
        assert len(status.workers) == 1, (
            f"terminated worker must deregister: {status.workers}"
        )
    finally:
        th.join()
        for p in workers:
            p.kill()


@pytest.mark.slow
def test_static_scheduling_posterior():
    """scheduling='static' (fixed acceptance quotas, the reference
    RedisStaticSampler variant) must recover the same conjugate posterior
    as the dynamic mode / MappingSampler."""
    s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                          generation_timeout=240.0, scheduling="static")
    port = s.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    try:
        abc = _abc(s, pop=80)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations == 3
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(0.8, abs=0.35)
        # exactly n accepted particles delivered, one per quota unit
        assert len(df) == 80
    finally:
        for p in workers:
            p.kill()
        s.stop()


@pytest.mark.slow
def test_look_ahead_posterior_unbiased_and_overlaps():
    """Mid-generation look-ahead (reference look_ahead_delay_evaluation):
    gen t+1 proposals are built from PRELIMINARY gen-t particles and
    evaluated by workers while the orchestrator persists/adapts; delayed
    acceptance against the final epsilon + importance weights wrt the
    proposal actually used keep the posterior EXACTLY as unbiased as the
    serial path."""
    results = {}
    for la in (True, False):
        s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                              generation_timeout=240.0, look_ahead=la)
        port = s.address[1]
        workers = [_spawn_worker(port) for _ in range(2)]
        try:
            abc = _abc(s, delay_s=0.002, pop=80)
            abc.new("sqlite://", {"x": X_OBS})
            if la:
                _throttle_persist(abc)
            t0 = time.time()
            h = abc.run(max_nr_populations=4)
            wall = time.time() - t0
            assert h.n_populations == 4
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            results[la] = (mu, wall, list(s.lookahead_head_starts))
        finally:
            for p in workers:
                p.kill()
            s.stop()
    mu_la, wall_la, head_starts = results[True]
    mu_serial, wall_serial, _ = results[False]
    # conjugate posterior mean 0.8 (prior N(0,1), noise sd 0.5);
    # tolerances calibrated to the measured per-run spread at these pop
    # sizes (see test_look_ahead_delayed_evaluation_adaptive_distance)
    assert mu_la == pytest.approx(0.8, abs=0.55)
    assert mu_serial == pytest.approx(0.8, abs=0.55)
    assert mu_la == pytest.approx(mu_serial, abs=0.7)
    # the overlap evidence: at least one adopted generation already had
    # worker results waiting when the orchestrator arrived (t+1 work ran
    # during gen-t finalization + persist + adapt)
    assert head_starts, "no generation was adopted from look-ahead"
    assert max(head_starts) > 0, head_starts
    # wall-time: record for the logs; on a 1-core CI box the overlap gain
    # is bounded by the orchestrator gap, so only guard against pathology
    assert wall_la < wall_serial * 1.5, (wall_la, wall_serial)


@pytest.mark.slow
def test_look_ahead_delayed_evaluation_adaptive_distance():
    """Full delayed-evaluation look-ahead (reference
    look_ahead_delay_evaluation): with AdaptivePNormDistance +
    QuantileEpsilon, preliminary workers only simulate — the
    orchestrator recomputes distance AND acceptance from the shipped sum
    stats once the generation's new weights and final epsilon exist. The
    posterior must match the serial path, adopted generations must show
    a head start, and persisted distances must equal the FINAL-weight
    distances (not the workers' stale-weight ones).

    Round-6 deflake, localized with the observability tracer's span log
    (broker.generation spans carry adopted/head_start; a repeated-run
    diagnostic showed the failure was adopted-generation ESS collapsing
    to ~9/60): preliminary proposals now ride a defensive prior mixture
    bounding the importance ratio (ABCSMC.lookahead_defensive_frac), the
    orchestrator's persist is throttled so adoption head starts test the
    overlap mechanism rather than scheduler timing, and the final
    generation's ESS is asserted as the regression guard."""
    results = {}
    for la in (True, False):
        s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                              generation_timeout=240.0, look_ahead=la,
                              look_ahead_frac=0.4)
        port = s.address[1]
        workers = [_spawn_worker(port) for _ in range(2)]
        tracer = pt.Tracer()
        try:
            prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
            dist = pt.AdaptivePNormDistance(p=2)
            abc = pt.ABCSMC(_host_model(0.002), prior, dist,
                            population_size=60,
                            eps=pt.QuantileEpsilon(initial_epsilon=1.5,
                                                   alpha=0.5),
                            sampler=s, seed=4, tracer=tracer)
            if la:
                assert abc._look_ahead_capable()
                assert abc._lookahead_recompute
            abc.new("sqlite://", {"x": X_OBS})
            if la:
                _throttle_persist(abc)
            h = abc.run(max_nr_populations=4)
            assert h.n_populations == 4
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            ess = float(1.0 / np.sum(np.asarray(w) ** 2))
            # persisted distances of the last generation must be the
            # FINAL-weight distances: recompute from stored sum stats
            # with the distance's weights for that generation
            wd = h.get_weighted_distances(h.max_t)
            _w_ss, stats = h.get_weighted_sum_stats(h.max_t)
            recomputed = np.array([
                dist({"x": float(stats[i, 0])}, {"x": X_OBS}, h.max_t)
                for i in range(len(stats))
            ])
            np.testing.assert_allclose(
                np.sort(wd["distance"].to_numpy()), np.sort(recomputed),
                rtol=1e-6,
            )
            results[la] = (mu, ess, list(s.lookahead_head_starts),
                           tracer.spans())
        finally:
            for p in workers:
                p.kill()
            s.stop()
    mu_la, ess_la, head_starts, spans = results[True]
    mu_serial, _ess_serial, _, _ = results[False]
    # statistical sanity, calibrated to the MEASURED run-to-run spread:
    # at pop 60 x 4 generations with unseeded worker RNG the per-run
    # posterior-mean sd is ~0.25 (round-6 20x campaign observed means
    # 0.46-1.22 on the SERIAL path), so 0.35 was ~1.4 sigma on the
    # difference and flaked at the expected rate under load. These are
    # sanity bounds; the unbiasedness proof is the tight guards below
    # (ESS, adoption, final-weight distances), which held 20/20.
    assert mu_la == pytest.approx(0.8, abs=0.55)
    assert mu_serial == pytest.approx(0.8, abs=0.55)
    assert mu_la == pytest.approx(mu_serial, abs=0.7)
    # regression guard for the round-5 flake: the defensive mixture
    # bounds importance ratios at 1/lookahead_defensive_frac, so the
    # adopted final generation cannot weight-collapse (observed 38-59
    # effective of 60 over repeated runs; 9/60 when it was broken)
    assert ess_la > 20.0, f"adopted-generation ESS collapsed: {ess_la}"
    # adoption + overlap evidence, from the span log: adopted
    # broker.generation spans exist and their head starts (results
    # already delivered when the orchestrator arrived — guaranteed a
    # window by the throttled persist) are positive
    adopted_spans = [sp for sp in spans
                     if sp.name == "broker.generation"
                     and sp.attrs.get("adopted")]
    assert adopted_spans, "no generation was adopted from look-ahead"
    assert head_starts and max(head_starts) > 0, head_starts
    assert max(sp.attrs.get("head_start", 0)
               for sp in adopted_spans) > 0


@pytest.mark.slow
def test_worker_catch_turns_model_errors_into_records():
    """Reference ``abc-redis-worker --catch``: a model that raises on a
    fraction of evaluations must NOT kill the worker loop — the failing
    evaluations ship as rejected error records, the generation completes
    from the healthy evaluations, and the errors surface on the sampler."""
    s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                          generation_timeout=240.0)
    port = s.address[1]
    workers = [_spawn_worker(port) for _ in range(2)]
    try:
        def flaky(pars):
            if np.random.random() < 0.2:
                raise RuntimeError("simulated model blow-up")
            return {"x": pars["theta"] + NOISE_SD * np.random.normal()}

        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(pt.SimpleModel(flaky, name="flaky"), prior,
                        pt.PNormDistance(p=2), population_size=60,
                        eps=pt.QuantileEpsilon(initial_epsilon=1.5,
                                               alpha=0.5),
                        sampler=s, seed=4)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations == 3
        df, w = h.get_distribution(0, h.max_t)
        assert len(df) == 60
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(0.8, abs=0.4)
        # ~20% of evaluations raised; the last generation's errors are on
        # the sampler, each carrying the exception repr
        assert s.error_records, "no error records surfaced"
        assert "simulated model blow-up" in s.error_records[0][1]
        # both workers are still alive (the loop survived the raises)
        assert all(p.poll() is None for p in workers)
    finally:
        for p in workers:
            p.kill()
        s.stop()


@pytest.mark.slow
def test_worker_processes_cli_option():
    """``abc-worker --processes N`` (reference parity) serves a run with N
    worker processes from one command."""
    s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                          generation_timeout=240.0)
    port = s.address[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # own session so teardown can kill the WHOLE group — SIGKILLing only
    # the wrapper parent would orphan the spawned worker grandchildren
    # for the rest of their runtime
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from pyabc_tpu.cli import worker_cmd; worker_cmd()",
         "127.0.0.1", str(port), "--processes", "2", "--runtime-s", "60"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True,
    )
    try:
        abc = _abc(s, pop=60)
        abc.new("sqlite://", {"x": X_OBS})
        seen_workers = set()

        def watch():
            while proc.poll() is None and len(seen_workers) < 2:
                try:
                    seen_workers.update(s.broker.status().workers)
                except Exception:
                    pass
                time.sleep(0.05)

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()
        h = abc.run(max_nr_populations=2)
        assert h.n_populations == 2
        watcher.join(timeout=5)
        assert len(seen_workers) >= 2, (
            f"expected 2 worker processes, saw {seen_workers}"
        )
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)
        s.stop()


def test_look_ahead_still_gated_off_for_stochastic_and_sumstat():
    """Delayed evaluation does NOT extend to ADAPTIVE probabilistic
    acceptance (pdf-norm feedback / Temperature schemes) or
    learned-sumstat distances; the gate must keep refusing those."""
    s = pt.ElasticSampler(host="127.0.0.1", port=0, look_ahead=True)
    try:
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(
            _host_model(), prior,
            pt.IndependentNormalKernel(var=[NOISE_SD ** 2]),
            population_size=40,
            eps=pt.Temperature(),
            acceptor=pt.StochasticAcceptor(),
            sampler=s, seed=4,
        )
        assert not abc._look_ahead_capable()
    finally:
        s.stop()


def _noisy_fixed_schedule_abc(s, seed=4, pop=60):
    """Fixed-schedule noisy config (round 8, VERDICT r5 #3): static
    kernel + pre-specified temperature ladder + analytic pdf norm —
    nothing in the acceptance rule depends on the adopted generation's
    records, so delayed stochastic acceptance is exact."""
    def sim(pars):  # noise lives in the kernel, not the model
        return {"x": pars["theta"]}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return pt.ABCSMC(
        pt.SimpleModel(sim, name="gauss_noisy"), prior,
        pt.IndependentNormalKernel(var=[NOISE_SD ** 2]),
        population_size=pop,
        eps=pt.ListTemperature([8.0, 4.0, 2.0, 1.0]),
        acceptor=pt.StochasticAcceptor(
            pdf_norm_method=pt.pdf_norm_from_kernel),
        sampler=s, seed=seed,
    )


def test_look_ahead_gate_opens_for_fixed_schedule_stochastic():
    """The round-8 gate extension: ListTemperature +
    pdf_norm_from_kernel + a static stochastic kernel rides look-ahead
    (with _lookahead_stochastic delayed acceptance); any adaptive
    ingredient — Temperature schemes or the max-found norm — keeps it
    closed."""
    s = pt.ElasticSampler(host="127.0.0.1", port=0, look_ahead=True)
    try:
        abc = _noisy_fixed_schedule_abc(s)
        assert abc._look_ahead_capable()
        assert abc._lookahead_stochastic
        assert not abc._lookahead_recompute
        # max-found pdf norm adapts from records -> closed
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc2 = pt.ABCSMC(
            pt.SimpleModel(lambda p: {"x": p["theta"]}, name="g"), prior,
            pt.IndependentNormalKernel(var=[NOISE_SD ** 2]),
            population_size=40,
            eps=pt.ListTemperature([4.0, 1.0]),
            acceptor=pt.StochasticAcceptor(),  # default: max_found
            sampler=s, seed=4,
        )
        assert not abc2._look_ahead_capable()
        assert not abc2._lookahead_stochastic
    finally:
        s.stop()


@pytest.mark.slow
def test_look_ahead_noisy_fixed_schedule_unbiased_with_ess_guard():
    """Look-ahead on the fixed-schedule noisy path: preliminary
    proposals ride the SAME variance guards as the uniform path
    (defensive prior mixture bounding importance ratios, builder-ESS
    floor, bandwidth widening — the payload builder is
    acceptor-agnostic), and delayed STOCHASTIC acceptance applies the
    exact rule host-side. Regression guards (ROADMAP noisy-path item):
    adopted generations exist with positive head starts, the posterior
    matches the serial noisy path, and the ADOPTED final generation's
    ESS has not collapsed."""
    results = {}
    for la in (True, False):
        s = pt.ElasticSampler(host="127.0.0.1", port=0, batch=5,
                              generation_timeout=240.0, look_ahead=la,
                              look_ahead_frac=0.4)
        port = s.address[1]
        workers = [_spawn_worker(port) for _ in range(2)]
        try:
            abc = _noisy_fixed_schedule_abc(s)
            abc.new("sqlite://", {"x": X_OBS})
            if la:
                assert abc._look_ahead_capable()
                assert abc._lookahead_stochastic
                _throttle_persist(abc)
            h = abc.run(max_nr_populations=4)
            assert h.n_populations == 4
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            ess = float(1.0 / np.sum(np.asarray(w) ** 2))
            results[la] = (mu, ess, list(s.lookahead_head_starts))
        finally:
            for p in workers:
                p.kill()
            s.stop()
    mu_la, ess_la, head_starts = results[True]
    mu_serial, _ess_serial, _ = results[False]
    # exact conjugate posterior mean 0.8 at T=1; tolerances follow the
    # calibrated spread of the uniform-path look-ahead tests
    assert mu_la == pytest.approx(0.8, abs=0.55)
    assert mu_serial == pytest.approx(0.8, abs=0.55)
    assert mu_la == pytest.approx(mu_serial, abs=0.7)
    # adoption + overlap evidence
    assert head_starts, "no generation was adopted from look-ahead"
    assert max(head_starts) > 0, head_starts
    # the variance-guard regression assertion (VERDICT r5 #3): the
    # adopted final generation must keep a healthy effective sample size
    # (defensive mixture bounds importance ratios at 1/frac; stochastic
    # above-norm excess weights stay bounded by the analytic pdf norm)
    assert ess_la > 20.0, f"adopted-generation ESS collapsed: {ess_la}"
