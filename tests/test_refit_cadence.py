"""Refit-cadence proposal engine (ISSUE 3 tentpole): drift guard,
amortized refits, posterior parity, observability wiring.

Statistical backdrop: sampling generation t+1 from a STALE LocalTransition
fit is exact — importance weights always use the proposal params actually
sampled from — so cadence trades only proposal freshness (acceptance
rate), never correctness. These tests pin that: the posterior must hold
even when refits are withheld entirely, and the drift guard must restore
refits exactly when the population moves.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.observability import MetricsRegistry, Tracer
from pyabc_tpu.transition.util import device_proposal_drift

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _run(refit_every, thr, *, seed=11, eps=None, gens=6, pop=300,
         metrics=None, tracer=None, distance=None):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(
        _gauss_model(), prior,
        distance if distance is not None
        else (pt.PNormDistance(p=2) if eps is not None
              else pt.AdaptivePNormDistance(p=2)),
        population_size=pop,
        eps=eps if eps is not None else pt.MedianEpsilon(),
        seed=seed, fused_generations=8,
        transitions=pt.LocalTransition(k_fraction=0.3),
        refit_every=refit_every, refit_drift_threshold=thr,
        metrics=metrics if metrics is not None else None,
        tracer=tracer,
    )
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=gens)
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    return abc, h, mu


# ----------------------------------------------------- drift statistic
def test_drift_zero_on_identical_population():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(100, 3)), jnp.float32)
    w = jnp.full((100,), 0.01, jnp.float32)
    vmask = jnp.ones((3,), jnp.float32)
    d = float(device_proposal_drift(X, w, X, w, vmask))
    assert d == pytest.approx(0.0, abs=1e-4)


def test_drift_detects_mean_and_var_shift():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.normal(size=(200, 2)), jnp.float32)
    w = jnp.full((200,), 1.0 / 200, jnp.float32)
    vmask = jnp.ones((2,), jnp.float32)
    # one-std mean shift -> drift ~ 1
    shifted = float(device_proposal_drift(X, w, X + 1.0, w, vmask))
    assert shifted == pytest.approx(1.0, abs=0.15)
    # variance halving -> |var_n - var_f| / var_f ~ 0.75
    contracted = float(device_proposal_drift(X, w, X * 0.5, w, vmask))
    assert contracted > 0.5
    # padded dims never contribute
    vmask0 = jnp.asarray([1.0, 0.0], jnp.float32)
    X2 = X.at[:, 1].add(100.0)
    assert float(device_proposal_drift(X, w, X2, w, vmask0)) \
        == pytest.approx(0.0, abs=1e-4)


def test_drift_zero_mass_returns_zero():
    import jax.numpy as jnp

    X = jnp.zeros((10, 2), jnp.float32)
    w0 = jnp.zeros((10,), jnp.float32)
    w1 = jnp.full((10,), 0.1, jnp.float32)
    vmask = jnp.ones((2,), jnp.float32)
    assert float(device_proposal_drift(X, w0, X, w1, vmask)) == 0.0
    assert float(device_proposal_drift(X, w1, X, w0, vmask)) == 0.0


# ------------------------------------------------------- cadence config
def test_refit_cadence_cfg_rules():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))

    def abc_with(**kw):
        return pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                         population_size=100, eps=pt.MedianEpsilon(), **kw)

    local = abc_with(transitions=pt.LocalTransition())
    # auto: off below the scale population, on at >= 16384
    assert local._refit_cadence_cfg(8192) is None
    assert local._refit_cadence_cfg(16384) == (16, 0.3)
    # explicit cadence applies at any population
    local2 = abc_with(transitions=pt.LocalTransition(), refit_every=4,
                      refit_drift_threshold=0.7)
    assert local2._refit_cadence_cfg(512) == (4, 0.7)
    # refit_every=1 IS the pre-cadence program
    local3 = abc_with(transitions=pt.LocalTransition(), refit_every=1)
    assert local3._refit_cadence_cfg(16384) is None
    # MVN never opts in (its refit is one weighted covariance)
    mvn = abc_with(refit_every=4)
    assert mvn._refit_cadence_cfg(16384) is None


# ----------------------------------------------- cadence + drift guard
def test_cadence_tick_refits_and_posterior_parity():
    """refit_every=4 with the drift guard disabled: refits exactly at
    the forced first generation and every 4th after, posterior parity
    with the every-generation run."""
    reg = MetricsRegistry()
    abc, h, mu = _run(4, 1e9, metrics=reg)
    assert h.n_populations == 6
    flags = [r for (_t, r, _d, _c) in abc.refit_events]
    assert flags == [True, False, False, False, True, False]
    assert reg.snapshot()["pyabc_tpu_refits_total"] == 2.0
    # drift is still MEASURED on every generation (histogram count == 6)
    assert reg.snapshot()["pyabc_tpu_refit_drift"]["count"] == 6
    _abc1, _h1, mu_every = _run(1, 1e9)
    assert mu == pytest.approx(POST_MU, abs=0.3)
    assert mu == pytest.approx(mu_every, abs=0.3)


def test_no_refit_at_all_posterior_still_exact():
    """The strongest parity statement: with refits withheld entirely
    (beyond the forced first fit) the proposal is maximally stale, yet
    the importance weights keep the posterior exact."""
    abc, h, mu = _run(1000, 1e9)
    assert h.n_populations == 6
    flags = [r for (_t, r, _d, _c) in abc.refit_events]
    assert flags[0] is True and not any(flags[1:])
    assert mu == pytest.approx(POST_MU, abs=0.3)


def test_drift_guard_fires_on_mid_chunk_shift():
    """A sharp epsilon drop mid-chunk contracts the accepted population;
    the drift statistic must cross the threshold EXACTLY there, trigger
    a refit, and posterior parity must hold (the ISSUE acceptance
    criterion)."""
    eps = pt.ListEpsilon([2.0, 1.6, 1.4, 0.35, 0.3])
    abc, h, mu = _run(1000, 0.6, eps=eps, gens=5)
    assert h.n_populations == 5
    events = abc.refit_events
    assert len(events) == 5
    # forced first fit, then quiet until the t=3 contraction
    assert events[0][1] is True
    assert events[1][1] is False and events[2][1] is False
    t3 = events[3]
    assert t3[1] is True and t3[2] > 0.6, events
    # drift values below the threshold on the no-trigger generations
    assert events[1][2] < 0.6 and events[2][2] < 0.6
    assert mu == pytest.approx(POST_MU, abs=0.3)


def test_refit_telemetry_and_metrics_visible():
    """Refit count, drift statistic and refit spans are visible in the
    observability metrics and History telemetry (ISSUE acceptance)."""
    reg = MetricsRegistry()
    tracer = Tracer()
    abc, h, _mu = _run(4, 1e9, metrics=reg, tracer=tracer)
    tel = h.get_telemetry(2)
    assert tel["refit"] is False
    assert "drift" in tel and tel["drift"] >= 0.0
    assert tel["refit_rows_changed"] == 0
    tel4 = h.get_telemetry(4)
    assert tel4["refit"] is True and tel4["refit_rows_changed"] > 0
    snap = reg.snapshot()
    assert snap["pyabc_tpu_refits_total"] == 2.0
    assert snap["pyabc_tpu_refit_rows_changed_total"] > 0
    assert snap["pyabc_tpu_refit_drift"]["count"] == 6
    # host-side mirror refits record "refit" WORK spans in the trace
    names = {s.name for s in tracer.spans()}
    assert "refit" in names


def test_cadence_chunk_events_carry_refit_counts():
    events = []
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
                    population_size=300, eps=pt.MedianEpsilon(), seed=7,
                    fused_generations=4,
                    transitions=pt.LocalTransition(k_fraction=0.3),
                    refit_every=4, refit_drift_threshold=1e9)
    abc.chunk_event_cb = events.append
    abc.new("sqlite://", {"x": X_OBS})
    abc.run(max_nr_populations=6)
    assert events and all("refits" in e for e in events if e["gens"])
    assert sum(e.get("refits", 0) for e in events) == 2
    assert any("drift_last" in e for e in events)


def test_cadence_off_keeps_legacy_outputs():
    """refit_every=1 (and every non-LocalTransition config): no refit
    keys in telemetry, no refit events — the pre-cadence program."""
    abc, h, _mu = _run(1, 1e9)
    assert abc.refit_events == []
    assert "refit" not in h.get_telemetry(2)
