"""Sub-mesh placement (round 15): the buddy allocator's books balance.

The serving contract under test: every device index is at all times in
exactly one of {free blocks, shared width-1 blocks, exclusive leases,
lost} — ``check_invariants()`` recomputes that partition from scratch,
and it must stay empty through allocation, packing, coalescing, device
loss (quarantine), degraded cordons and restores, including a seeded
randomized interleaving (the lease-table property-test pattern from
round 10)."""
import random

import pytest

from pyabc_tpu.serving.placement import (
    SubMeshAllocator,
    _aligned_blocks,
    feasible_widths,
)


def test_aligned_seed_decomposition():
    assert _aligned_blocks(0, 8) == [(0, 8)]
    assert _aligned_blocks(0, 5) == [(0, 4), (4, 1)]
    assert _aligned_blocks(0, 6) == [(0, 4), (4, 2)]


def test_alloc_free_coalesce_roundtrip():
    a = SubMeshAllocator(8)
    assert a.check_invariants() == []
    assert a.alloc(4, "big") == 0
    assert a.alloc(2, "mid") == 4
    assert a.alloc(1, "s1") == 6
    assert a.alloc(1, "s2") == 7
    assert a.widest_free() == 0
    assert a.alloc(1, "nope") is None
    assert a.check_invariants() == []
    # frees coalesce back to one full-width block
    for owner in ("big", "mid", "s1", "s2"):
        a.free(owner)
        assert a.check_invariants() == []
    assert a.widest_free() == 8
    assert a.coalesces_total >= 3


def test_width_must_be_power_of_two_and_single_lease_per_owner():
    a = SubMeshAllocator(8)
    with pytest.raises(ValueError):
        a.alloc(3, "x")
    a.alloc(2, "x")
    with pytest.raises(ValueError):
        a.alloc(1, "x")  # one lease per owner
    with pytest.raises(KeyError):
        a.free("never-leased")


def test_packing_shares_width1_blocks_densely():
    a = SubMeshAllocator(8, packing=2)
    assert a.alloc(1, "a") == a.alloc(1, "b")  # same shared block
    assert a.alloc(1, "c") != a._owner_shared["a"]  # third opens a new one
    # wide leases never share
    assert a.alloc(4, "wide") == 4
    assert a.check_invariants() == []
    a.free("a")
    # block still held by b: not freed, not coalesced
    assert a.lease_of("b") is not None
    a.free("b")
    a.free("c")
    a.free("wide")
    assert a.widest_free() == 8 and a.check_invariants() == []


def test_device_loss_in_free_block_splits_and_quarantines():
    a = SubMeshAllocator(8)
    assert a.mark_lost([5]) == []  # nothing leased: no one affected
    assert a.healthy_count() == 7
    assert a.check_invariants() == []
    # 5 is quarantined: the widest allocatable block is the clean half
    assert a.widest_free() == 4
    assert a.alloc(4, "w") == 0
    # re-losing the same device is idempotent
    assert a.mark_lost([5]) == []
    assert a.healthy_count() == 7


def test_device_loss_under_lease_reports_owner_and_quarantines_on_free():
    a = SubMeshAllocator(8)
    assert a.alloc(4, "t") == 0
    assert a.mark_lost([2]) == ["t"]
    # the lease itself stays (the scheduler reaps it); freeing it
    # returns only the healthy survivors
    a.free("t")
    assert a.check_invariants() == []
    assert a.healthy_count() == 7
    assert a.free_device_count() == 7
    # the lost device never re-enters a free list
    assert a.widest_free() == 4


def test_shared_block_loss_reports_every_packed_owner():
    a = SubMeshAllocator(2, packing=3)
    a.alloc(1, "a")
    a.alloc(1, "b")
    lo = a._owner_shared["a"]
    assert a.mark_lost([lo]) == ["a", "b"]
    a.free("a")
    a.free("b")
    assert a.check_invariants() == []
    assert a.healthy_count() == 1


def test_degraded_cordons_subblocks_but_existing_leases_drain():
    a = SubMeshAllocator(8)
    assert a.alloc(2, "keep") == 0
    a.mark_degraded([2, 3])
    # the cordon blocks NEW placements on 2-3, the clean half still serves
    assert a.alloc(4, "w") == 4
    assert a.alloc(2, "no") is None
    a.restore([2, 3])
    assert a.alloc(2, "yes") == 2
    assert a.check_invariants() == []


def test_restore_returns_lost_devices_and_recoalesces():
    a = SubMeshAllocator(8)
    a.mark_lost([3])
    assert a.widest_free() == 4
    a.restore([3])
    assert a.healthy_count() == 8
    assert a.widest_free() == 8
    assert a.check_invariants() == []


def test_non_power_of_two_pool():
    a = SubMeshAllocator(5)
    assert a.check_invariants() == []
    assert a.alloc(4, "w") == 0
    assert a.alloc(1, "s") == 4
    a.free("w")
    a.free("s")
    assert a.widest_free() == 4
    assert a.check_invariants() == []


def test_feasible_widths_policy():
    assert feasible_widths(None) == [1]
    assert feasible_widths(1) == [1]
    assert feasible_widths(4) == [4, 2, 1]
    assert feasible_widths(8) == [8, 4, 2, 1]
    with pytest.raises(ValueError):
        feasible_widths(6)


def test_randomized_interleaving_books_always_balance():
    """The property test: 4000 seeded random alloc/free/lose/restore
    operations; after EVERY op the partition recomputes clean — zero
    leaked, overlapping or double-booked device ranges."""
    rng = random.Random(0)
    a = SubMeshAllocator(8, packing=3)
    live: dict[str, int] = {}
    for i in range(4000):
        op = rng.random()
        if op < 0.45 or not live:
            got = a.alloc(rng.choice([1, 1, 1, 2, 4, 8]), f"o{i}")
            if got is not None:
                live[f"o{i}"] = got
        elif op < 0.85:
            owner = rng.choice(sorted(live))
            a.free(owner)
            del live[owner]
        elif op < 0.92:
            for owner in a.mark_lost([rng.randrange(8)]):
                a.free(owner)  # the scheduler's reap-then-free path
                del live[owner]
        else:
            a.restore([rng.randrange(8)])
        assert a.check_invariants() == [], (i, a.check_invariants())
    stats = a.stats()
    assert stats["allocs_total"] == a.allocs_total >= 1
    assert stats["frees_total"] == a.frees_total >= 1


def test_build_mesh_physical_vs_virtual():
    """Width-1 and beyond-platform leases are logical (None: the tenant
    runs its shards virtually); in-platform wide leases get a real Mesh
    over exactly the leased devices (conftest forces 8 CPU devices)."""
    import jax

    from pyabc_tpu.serving.placement import (
        build_mesh,
        platform_device_count,
    )

    n = platform_device_count()
    assert n == len(jax.devices())
    assert build_mesh(0, 1) is None
    assert build_mesh(n, 2) is None  # beyond the platform: virtual
    if n >= 4:
        mesh = build_mesh(2, 2)
        devs = list(mesh.devices.flat)
        assert [d.id for d in devs] == [jax.devices()[2].id,
                                        jax.devices()[3].id]


# ------------------------------------------------- round 18: host segments
#
# The device line becomes per-host segments: buddy alignment makes host
# confinement free for widths <= devices_per_host, widths above need an
# explicit multi_host lease, and host loss quarantines a whole segment in
# one step (counted separately from chip loss).

def test_host_pool_validation():
    with pytest.raises(ValueError, match="split evenly"):
        SubMeshAllocator(8, n_hosts=3)
    with pytest.raises(ValueError, match="power of two"):
        SubMeshAllocator(12, n_hosts=2)  # 6 devices/host
    a = SubMeshAllocator(8, n_hosts=2)
    assert a.devices_per_host == 4
    assert [a.host_of(d) for d in (0, 3, 4, 7)] == [0, 0, 1, 1]
    assert a.stats()["n_hosts"] == 2


def test_leases_never_straddle_hosts_implicitly():
    a = SubMeshAllocator(8, n_hosts=2)
    with pytest.raises(ValueError, match="straddle hosts"):
        a.alloc(8, "wide")
    # host-confinable widths pack into single segments, aligned
    assert a.alloc(4, "t0") == 0
    assert a.alloc(4, "t1") == 4
    assert a.host_of(0) == 0 and a.host_of(4) == 1
    assert a.check_invariants() == []


def test_multi_host_flag_allows_whole_host_spans():
    a = SubMeshAllocator(8, n_hosts=2)
    assert a.alloc(8, "wide", multi_host=True) == 0
    assert a.check_invariants() == []
    a.free("wide")
    assert a.widest_free() == 8


def test_single_host_pool_unaffected_by_straddle_guard():
    """n_hosts=1 (the round-15 default): no straddle guard, alloc keeps
    its old contract (None when nothing fits, never a new raise)."""
    a = SubMeshAllocator(8)
    assert a.alloc(8, "wide") == 0
    assert a.alloc(1, "later") is None


def test_mark_host_lost_reaps_segment_and_counts_once():
    a = SubMeshAllocator(8, n_hosts=2)
    assert a.alloc(4, "t0") == 0
    assert a.alloc(2, "t1") == 4
    affected = a.mark_host_lost(1)
    assert affected == ["t1"]
    assert a.healthy_count() == 4
    assert a.stats()["lost_hosts"] == [1]
    assert a.hosts_lost_total == 1
    # a second loss of the SAME (already dead) host is idempotent:
    # nothing newly affected, the host counter does not double-count
    assert a.mark_host_lost(1) == []
    assert a.hosts_lost_total == 1
    # the lease reaps scheduler-side; freeing quarantines the segment
    a.free("t1")
    assert a.widest_free() == 0  # t0 still holds host 0
    a.free("t0")
    assert a.widest_free() == 4
    assert a.check_invariants() == []
    with pytest.raises(ValueError, match="out of range"):
        a.mark_host_lost(2)


def test_host_restore_clears_lost_host_set():
    a = SubMeshAllocator(8, n_hosts=2)
    a.mark_host_lost(0)
    assert a.stats()["lost_hosts"] == [0]
    a.restore([0, 1])  # partial repair: segment still has lost chips
    assert a.stats()["lost_hosts"] == [0]
    a.restore([2, 3])
    assert a.stats()["lost_hosts"] == []
    assert a.healthy_count() == 8
    assert a.check_invariants() == []


def test_multi_host_lease_dies_with_any_host():
    a = SubMeshAllocator(8, n_hosts=2)
    assert a.alloc(8, "wide", multi_host=True) == 0
    assert a.mark_host_lost(1) == ["wide"]
    a.free("wide")
    # host 0's half comes back; host 1's segment stays quarantined
    assert a.widest_free() == 4
    assert a.check_invariants() == []
