"""Unit tests for bench.py's spend-loop accounting and resilience.

The real bench needs the TPU tunnel; these tests patch
``run_tpu_bench`` with fakes so the loop logic — overlapped-run
finalization, the one-off-failure retry, and the dual-basis headline
computation — is exercised deterministically in milliseconds. This is
the logic the driver's one capture per round depends on.
"""
import importlib.util
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench(monkeypatch, tmp_path, capsys):
    import signal as signal_mod

    # bench.py installs SIGTERM/SIGINT handlers (os._exit on fire) and an
    # atexit emit hook at import — save/restore the handlers so a Ctrl-C
    # later in the pytest session still reaches pytest, and neuter the
    # module's emit at teardown so its atexit hook is a no-op
    old_term = signal_mod.getsignal(signal_mod.SIGTERM)
    old_int = signal_mod.getsignal(signal_mod.SIGINT)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # skip the jax platform probe subprocess and the host-baseline run,
    # and the elastic + resilience lanes (they spawn REAL worker
    # subprocesses — the loop tests drive a virtual clock; the lanes
    # have their own unit tests)
    monkeypatch.setenv("PYABC_TPU_BENCH_CPU", "1")
    monkeypatch.setenv("PYABC_TPU_BENCH_ELASTIC", "0")
    monkeypatch.setenv("PYABC_TPU_BENCH_RESILIENCE", "0")
    # the health + dispatch lanes run REAL fused runs on the shared
    # tracer; these tests drive main() with fake runs and assert
    # span-free coverage
    monkeypatch.setenv("PYABC_TPU_BENCH_HEALTH", "0")
    monkeypatch.setenv("PYABC_TPU_BENCH_DISPATCH", "0")
    # the mesh lane spawns a REAL forced-8-device subprocess; it has its
    # own unit tests (tests/test_sharded.py) and a live child smoke
    monkeypatch.setenv("PYABC_TPU_BENCH_MESH", "0")
    # the serve lane runs REAL tenant fleets on a RunScheduler (its own
    # tests: tests/test_serving.py); these loop tests drive a virtual
    # clock the scheduler's deadlines must not live on
    monkeypatch.setenv("PYABC_TPU_BENCH_SERVE", "0")
    monkeypatch.setattr(mod, "probe_platform", lambda *a, **k: "cpu")
    monkeypatch.setattr(mod, "run_host_baseline", lambda **k: 800.0)
    monkeypatch.setattr(
        mod, "HERE", str(tmp_path)
    )  # .baseline_pps cache goes to tmp
    monkeypatch.setenv("PYABC_TPU_BENCH_BUDGET_S", "1000")
    yield mod
    mod._emitted = True  # atexit hook becomes a no-op
    signal_mod.signal(signal_mod.SIGTERM, old_term)
    signal_mod.signal(signal_mod.SIGINT, old_int)


class FakeHistory:
    def get_all_populations(self):
        import pandas as pd

        return pd.DataFrame({"t": list(range(-1, 32))})

    def close(self):
        pass


class FakeSyncLedger:
    """Shape-compatible stand-in for observability.SyncLedger."""

    def summary(self, sync_floor_s):
        return {
            "syncs": 7,
            "by_kind": {"chunk_fetch": 4, "compute_probe": 3},
            "bytes_by_kind": {"chunk_fetch": 4 * 96_000},
            "total_bytes": 4 * 96_000,
            "sync_floor_s": sync_floor_s,
            "tunnel_floor_s": round(7 * sync_floor_s, 6),
        }


class FakeAbc:
    def __init__(self):
        self.history = FakeHistory()
        self.probe_events = [(0.0, 0.1), (0.1, 0.2)]
        self.drain_joined = False
        self.sync_ledger = FakeSyncLedger()

    def drain_join(self):
        self.drain_joined = True


def _fake_run_factory(clock, fail_seeds=(), run_wall=0.5, gens=32,
                      pop=1000):
    """A run_tpu_bench fake: advances a virtual wall clock and fires
    chunk events like a real overlapped run would."""

    def fake(pop_size, n_gens, budget_s, seed, prev_abc, on_event,
             prebuilt=None):
        if seed in fail_seeds:
            raise RuntimeError(f"synthetic failure on seed {seed}")
        for ci in range(1, 5):
            clock[0] += run_wall / 4
            on_event({
                "ts": clock[0], "t_first": (ci - 1) * 8, "gens": 8,
                "n_acc": pop * 8, "chunk_index": ci,
                "chunk_s": run_wall / 4, "fetch_s": 0.002,
                # post-compaction wire bytes vs the r5 full-f32-ring
                # equivalent (12 vs 32 B/row at d=4, pop 1000, G=8)
                "fetch_bytes": 96_000, "fetch_bytes_full_f32": 256_000,
                "dispatch_s": 0.001, "process_s": 0.0005,
            })
        return FakeAbc(), {"run_s_excl_drain": run_wall,
                           "adopted_kernels": seed > 0}

    return fake


class _ListClock:
    """Observability-clock adapter over the tests' mutable [t] cell."""

    def __init__(self, cell):
        self._cell = cell

    def now(self):
        return self._cell[0]

    def wall(self):
        return self._cell[0]


def _run_main_briefly(bench, monkeypatch, fake, clock, budget=30):
    """Run main() on a VIRTUAL clock the fake runs advance (each fake
    run consumes run_wall virtual seconds), so the spend loop
    terminates deterministically regardless of real wall time. The
    clock rides the observability subsystem's injection seam
    (bench.CLOCK) — bench code never calls time.time() directly."""
    monkeypatch.setenv("PYABC_TPU_BENCH_BUDGET_S", str(budget))
    monkeypatch.setattr(bench, "run_tpu_bench", fake)
    # the spend loop pre-builds run k+1's host objects on a setup thread;
    # the real builder constructs a full ABCSMC — fake it out
    monkeypatch.setattr(
        bench, "build_bench_run",
        lambda pop, seed, prev_abc: (FakeAbc(), prev_abc is not None),
    )
    monkeypatch.setattr(bench, "CLOCK", _ListClock(clock))
    monkeypatch.setattr(bench, "TRACER", None)  # main() rebuilds on CLOCK
    bench._emitted = False
    bench.main()


def test_headline_both_bases_and_full_coverage(bench, monkeypatch, capsys):
    clock = [time.time()]
    _run_main_briefly(bench, monkeypatch, _fake_run_factory(clock), clock)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(out)
    assert d["partial"] is False
    assert d["value"] > 0
    assert d["vs_baseline"] == pytest.approx(d["value"] / 800.0, rel=1e-3)
    assert "wall_clock" in d and d["wall_clock"]["aggregate_pps"] > 0
    assert "util" in d and "device_busy_frac_upper" in d["util"]
    # round-6 payload + sync telemetry: compaction ratio and sync counts
    # are regression-guarded metrics in the bench JSON
    assert d["util"]["fetch_bytes_per_chunk"] == 96_000
    assert d["util"]["fetch_bytes_per_chunk_r5_equiv"] == 256_000
    assert d["util"]["fetch_payload_reduction_x"] == pytest.approx(
        256_000 / 96_000, abs=0.01)
    assert d["util"]["syncs_per_run"] == 7
    assert d["util"]["tunnel_floor_s_per_run"] == pytest.approx(
        7 * d["util"]["sync_floor_s"], abs=1e-6)
    # the residual-gap attribution block: warm-run syncs x floor vs the
    # steady span's dark time (fake runs record no spans -> dark 0 ->
    # the model explains everything)
    gap = d["gap_attribution"]
    assert gap["warm_run_syncs_total"] >= 7
    assert 0.0 <= gap["dark_explained_by_sync_floor_frac"] <= 1.0
    # the BENCH observability block: coverage-accountant output is always
    # present (fake runs record no spans, so the fraction is just 0)
    obs = d["observability"]
    assert obs["n_spans"] == 0
    assert obs["steady_attributed_frac"] == 0.0
    assert [r["run"] for r in obs["per_warm_run"]] == sorted(
        r["run"] for r in obs["per_warm_run"]
    )
    assert all(0.0 <= r["attributed_frac"] <= 1.0
               for r in obs["per_warm_run"])
    # every warm run is finalized with its generation count
    gens = [r.get("generations_completed") for r in d["runs"]
            if "error" not in r and "elided_runs" not in r]
    assert gens and all(g == 32 for g in gens)
    # lanes are never silent: the fixture disables the elastic and
    # resilience lanes, so their recorded skip reasons must appear
    assert d["elastic"]["skipped"].startswith("disabled")
    assert d["resilience"]["skipped"].startswith("disabled")
    assert d["mesh"]["skipped"].startswith("disabled")


def test_one_off_failure_retries_and_completes(bench, monkeypatch, capsys):
    clock = [time.time()]
    fake = _fake_run_factory(clock, fail_seeds=(1,))
    _run_main_briefly(bench, monkeypatch, fake, clock)
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    errors = [r for r in d["runs"] if "error" in r]
    assert len(errors) == 1 and "seed" in errors[0]
    # the bench recovered: non-partial with steady runs after the failure
    assert d["partial"] is False
    assert d.get("n_steady_runs", 0) >= 1


def test_two_consecutive_failures_stop_the_bench(bench, monkeypatch,
                                                 capsys):
    clock = [time.time()]
    fake = _fake_run_factory(clock, fail_seeds=(1, 2))
    _run_main_briefly(bench, monkeypatch, fake, clock)
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    errors = [r for r in d["runs"] if "error" in r]
    assert len(errors) == 2
    # seed 0's warmup completed, so the emit still carries its info
    assert any(r.get("generations_completed") == 32 for r in d["runs"]
               if "error" not in r and "elided_runs" not in r)


def test_seed_zero_failure_aborts_cleanly(bench, monkeypatch, capsys):
    clock = [time.time()]
    fake = _fake_run_factory(clock, fail_seeds=(0,))
    _run_main_briefly(bench, monkeypatch, fake, clock)
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["partial"] is True  # nothing measured, honestly labeled
    assert any("error" in r for r in d["runs"])


def test_storage_lane_measures_both_stores(bench, monkeypatch):
    """The round-17 History-ingest lane: real (small) ingests against
    both backends, the >=10x regression guard evaluated, bytes per
    particle + the WAL delta in the util block."""
    from pyabc_tpu.observability import SYSTEM_CLOCK
    from pyabc_tpu.storage.columnar import has_pyarrow

    monkeypatch.setattr(bench, "CLOCK", SYSTEM_CLOCK)
    monkeypatch.setenv("PYABC_TPU_BENCH_STORAGE_POP", "512")
    monkeypatch.setenv("PYABC_TPU_BENCH_STORAGE_GENS", "2")
    out = bench.run_storage_lane(60.0)
    assert out["rows_store"]["rows_per_sec"] > 0
    assert out["rows_store"]["bytes_per_particle"] > 0
    assert out["wal_speedup_x"] > 0
    assert "history_bytes_per_particle_rows" in out["util"]
    if has_pyarrow():
        assert out["columnar_store"]["rows_per_sec"] > 0
        # the 10x acceptance line is asserted at pop-16384 scale by the
        # real lane run (BASELINE.md round 17); at pop 512 the parquet
        # framing overhead only allows a weaker sanity bound
        assert out["ingest_ratio_columnar_vs_rows"] > 1.0
        assert isinstance(out["guard_ok"], bool)
        assert (out["columnar_store"]["bytes_per_particle"]
                < out["rows_store"]["bytes_per_particle"])
    else:
        assert out["columnar_store"] == {"skipped": "pyarrow not installed"}
        assert out["guard_ok"] is None
