"""Precision audit for the narrowed fetch payload (round-6 compaction).

The fused loop ships accepted rows (theta / distance / log_weight, plus
retained sum stats) over the device->host link in a narrowed dtype
(``ABCSMC(fetch_dtype=...)``, float16 default — ops/pack.py). The device
carry chain stays f32, so the inference TRAJECTORY — which particles are
accepted, the epsilon trail, the in-kernel refits — is bit-identical
across fetch dtypes; only the History-persisted row values round through
the wire format. These tests are the documented audit that the rounding
can never silently corrupt History:

- row-wise parity against the f32 wire on the SAME trajectory (same
  seed + adopted kernels) within the dtype's relative ULP;
- posterior parity: weighted mean / variance of every generation within
  tolerances far tighter than statistical error;
- the acceptance invariant ``stored distance <= stored epsilon``
  survives narrowing (the distance column rounds toward zero — a
  round-to-nearest cast can push a stored distance half a ULP above the
  stored threshold);
- the conjugate-Gaussian posterior itself stays correct end to end.
"""
import numpy as np
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)

#: relative ULP of the narrowed formats (10 / 8 mantissa bits); the
#: monotone-down distance cast may consume up to ~1.5 ULP extra
REL_TOL = {"float16": 2.0 ** -10, "bfloat16": 2.0 ** -7}

N_GENS = 5
POP = 400


def _gauss_model():
    import jax

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _run(fetch_dtype, *, adopt_from=None, store_ss=True):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(
        _gauss_model(), prior, pt.AdaptivePNormDistance(p=2),
        population_size=POP, eps=pt.MedianEpsilon(), seed=7,
        fused_generations=4, fetch_dtype=fetch_dtype,
    )
    abc.new("sqlite://", {"x": X_OBS}, store_sum_stats=store_ss)
    if adopt_from is not None:
        abc.adopt_device_context(adopt_from)
    h = abc.run(max_nr_populations=N_GENS)
    assert h.n_populations == N_GENS
    return abc, h


@pytest.fixture(scope="module")
def f32_run():
    return _run("float32")


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_narrowed_fetch_posterior_parity(dtype, f32_run):
    """Same seed + adopted kernels => same trajectory; every generation's
    weighted mean/variance must match the f32 wire within the narrowed
    dtype's precision — far inside any statistically meaningful shift."""
    abc32, h32 = f32_run
    _abc, h = _run(dtype, adopt_from=abc32)
    rel = REL_TOL[dtype]
    # identical trajectory: the epsilon trail is computed on device in
    # f32 and fetched as f32 scalars regardless of the row wire format
    eps32 = h32.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    np.testing.assert_array_equal(eps, eps32)
    for t in range(N_GENS):
        df32, w32 = h32.get_distribution(0, t)
        df, w = h.get_distribution(0, t)
        assert len(df) == len(df32) == POP
        th32 = df32["theta"].to_numpy()
        th = df["theta"].to_numpy()
        # row-wise wire rounding only (same particles, same order)
        np.testing.assert_allclose(th, th32, rtol=rel, atol=rel)
        # posterior estimates: rounding noise averages DOWN across rows,
        # so the weighted moments sit well inside one ULP
        mu32 = float(np.sum(th32 * w32))
        mu = float(np.sum(th * w))
        var32 = float(np.sum(w32 * (th32 - mu32) ** 2))
        var = float(np.sum(w * (th - mu) ** 2))
        assert mu == pytest.approx(mu32, abs=2 * rel * max(1.0, abs(mu32)))
        assert var == pytest.approx(var32, rel=4 * rel, abs=4 * rel * var32
                                    + 1e-12)
        # weights themselves round through the wire (log-space cast)
        np.testing.assert_allclose(np.sort(w), np.sort(w32),
                                   rtol=8 * rel, atol=8 * rel / POP)


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_narrowed_fetch_acceptance_invariant(dtype):
    """Stored accepted distances must never exceed the stored epsilon of
    a QUANTILE schedule's next generation use — i.e. the in-generation
    invariant d <= eps_used survives the wire (monotone-down cast)."""
    _abc, h = _run(dtype)
    pops = h.get_all_populations().query("t >= 0")
    for t, eps_used in zip(pops["t"], pops["epsilon"]):
        if not np.isfinite(eps_used):
            continue  # generation 0 accepts at +inf
        d = h.get_weighted_distances(int(t))["distance"].to_numpy()
        assert float(d.max()) <= float(eps_used) + 1e-12, (
            f"t={t}: stored distance {d.max()} exceeds stored epsilon "
            f"{eps_used} after {dtype} narrowing"
        )


@pytest.mark.parametrize("dtype", ["float16", "bfloat16", "float32"])
def test_narrowed_fetch_conjugate_posterior(dtype):
    """End-to-end statistical correctness on the conjugate Gaussian: the
    analytic posterior is recovered identically well for every wire
    format (History round-trip tolerance, SURVEY §6 parity bar)."""
    _abc, h = _run(dtype)
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"].to_numpy() * w))
    assert mu == pytest.approx(POST_MU, abs=0.25)
    # sum stats round-trip the db in the narrowed dtype's precision
    _w_ss, stats = h.get_weighted_sum_stats(h.max_t)
    assert np.isfinite(stats).all()


def test_fetch_dtype_validated_at_construction():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    with pytest.raises(ValueError, match="fetch_dtype"):
        pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                  population_size=10, fetch_dtype="float8")
