"""Tenant lifecycle (round 19): retention GC, quotas, archival.

The contracts under test, directly against the storage + lifecycle
layers (no scheduler, no jax — the serving-integration legs live in
tests/test_serving.py and tests/test_traffic.py):

1. PRUNE-BEFORE — ``History.prune_before(t)`` drops the OLDEST
   generations (SQL rows AND columnar Parquet files) and never touches
   the PRE_TIME observed row or the newest generation — the resume
   seam survives any retention setting.
2. ARCHIVE ROUND-TRIP — ``archive_tenant_db`` packs db + columnar
   sidecar into one tar.gz and removes the originals;
   ``restore_tenant_db`` brings them back with every
   ``get_distribution`` read bit-identical.
3. QUOTAS — ``TenantQuota.check_spec`` rejects NON-RETRYABLY
   (retry_after_s None -> HTTP 400, the client must not loop), and the
   remaining-view arithmetic clamps at zero.
4. SWEEP — keep-last-k GC, byte-quota shedding (never below the newest
   generation), TTL disposal and the fleet byte budget, all on an
   injected VirtualClock; RUNNING tenants are never touched.
"""
import os
import tarfile
from pathlib import Path

import numpy as np
import pytest

from pyabc_tpu.core.parameters import ParameterSpace
from pyabc_tpu.core.population import Population
from pyabc_tpu.core.sumstat_spec import SumStatSpec
from pyabc_tpu.observability import VirtualClock
from pyabc_tpu.sampler.base import Sample, exp_normalize_log_weights
from pyabc_tpu.serving import AdmissionRejectedError, TenantSpec
from pyabc_tpu.serving.lifecycle import (
    LifecycleManager,
    RetentionPolicy,
    TenantQuota,
    disk_usage,
)
from pyabc_tpu.storage import (
    History,
    archive_tenant_db,
    restore_tenant_db,
)
from pyabc_tpu.storage.archive import archive_paths
from pyabc_tpu.storage.columnar import has_pyarrow

N, D, S = 80, 2, 3
MODEL_NAMES = ["m0"]
PARAM_NAMES = [["a", "b"]]


def _population(seed: int) -> Population:
    r = np.random.default_rng(seed)
    sample = Sample()
    sample.set_accepted(
        ms=np.zeros(N, np.int32),
        thetas=r.normal(size=(N, D)),
        weights=exp_normalize_log_weights(r.normal(size=N)),
        distances=np.abs(r.normal(size=N)),
        sumstats=r.normal(size=(N, S)),
        proposal_ids=np.arange(N),
    )
    return Population(
        ms=sample.ms, thetas=sample.thetas, weights=sample.weights,
        distances=sample.distances, sumstats=sample.sumstats,
        spaces=[ParameterSpace(n) for n in PARAM_NAMES],
        sumstat_spec=SumStatSpec({"x": np.zeros(S)}),
        model_names=MODEL_NAMES,
    )


def _make_history(db_url: str, gens: int = 4) -> None:
    h = History(db_url)
    h.store_initial_data(None, {}, {"x": np.zeros(S)}, {"a": 1.0},
                         MODEL_NAMES, "{}", "{}", "{}")
    for t in range(gens):
        h.append_population(t, 1.0 - 0.1 * t, _population(300 + t),
                            3 * N, MODEL_NAMES)
    h.close()


def _distributions(db_url: str) -> list:
    h = History(db_url)
    out = []
    for t in range(h.n_populations):
        eps = h.get_all_populations().query("t >= 0")["epsilon"]
        df, w = h.get_distribution(0, h.max_t - h.n_populations + 1 + t)
        out.append((np.asarray(eps), df.to_numpy(), np.asarray(w)))
    h.close()
    return out


class FakeTenant:
    """The attribute surface LifecycleManager touches, no scheduler."""

    def __init__(self, tmp_path, tid: str, scheme: str = "sqlite",
                 gens: int = 4, state: str = "completed",
                 finished_at: float | None = 0.0):
        from pyabc_tpu.observability import MetricsRegistry

        self.id = tid
        self.db_path = f"{scheme}:///{tmp_path}/{tid}.db"
        self.checkpoint_path = str(tmp_path / f"{tid}.ck")
        self.abc_id = 1
        self.state = state
        self.disposed = False
        self.finished_at = finished_at
        self.generations_done = gens
        self.chip_s = 0.0
        self.bytes_on_disk = 0
        self.metrics = MetricsRegistry()
        self.events: list = []
        if gens:
            _make_history(self.db_path, gens=gens)

    def record_event(self, kind, **attrs):
        self.events.append({"kind": kind, **attrs})


# ======================================================== prune_before
def test_prune_before_drops_oldest_keeps_resume_seam(
        tmp_path, store_scheme):
    db = f"{store_scheme}:///{tmp_path}/t.db"
    _make_history(db, gens=4)
    h = History(db)
    assert h.n_populations == 4
    removed = h.prune_before(2)
    assert removed == 2
    assert h.n_populations == 2 and h.max_t == 3
    # the PRE_TIME observed row survives: load()'s seam
    assert h.get_observed_sum_stat() is not None
    ts = h.get_all_populations().query("t >= 0")["t"].to_list()
    assert sorted(ts) == [2, 3]
    # surviving generations read back whole
    df, w = h.get_distribution(0, 3)
    assert len(w) == N and len(df) == N
    h.vacuum()
    h.close()
    if "columnar" in store_scheme:
        col = Path(str(tmp_path / "t.db") + ".columnar")
        names = sorted(p.name for p in col.rglob("*.parquet"))
        assert names == ["t2.parquet", "t3.parquet"]


def test_prune_before_never_drops_newest(tmp_path):
    db = f"sqlite:///{tmp_path}/t.db"
    _make_history(db, gens=3)
    h = History(db)
    # an over-eager cut still leaves nothing above max_t untouched:
    # prune_before(max_t) keeps exactly the newest
    assert h.prune_before(h.max_t) == 2
    assert h.n_populations == 1 and h.max_t == 2
    h.close()


# ====================================================== archive round-trip
def test_archive_roundtrip_restores_bit_identical(
        tmp_path, store_scheme):
    db = f"{store_scheme}:///{tmp_path}/t.db"
    _make_history(db, gens=3)
    before = _distributions(db)
    sql_path, col_dir, archive = archive_paths(db)

    out = archive_tenant_db(db)
    assert out == archive and archive.is_file()
    assert not sql_path.exists()
    assert not col_dir.exists()
    with tarfile.open(archive) as tf:
        names = tf.getnames()
    assert "db" in names
    if "columnar" in store_scheme:
        assert any(n.startswith("columnar/") for n in names)

    restore_tenant_db(db, remove_archive=True)
    assert sql_path.is_file() and not archive.exists()
    after = _distributions(db)
    assert len(before) == len(after)
    for (ea, da, wa), (eb, db_, wb) in zip(before, after):
        assert np.array_equal(ea, eb)
        assert np.array_equal(da, db_)
        assert np.array_equal(wa, wb)


# =============================================================== quotas
def test_quota_check_spec_rejects_non_retryable():
    quota = TenantQuota(max_generations=4)
    quota.check_spec(TenantSpec(model="gaussian", generations=4))
    with pytest.raises(AdmissionRejectedError) as exc_info:
        quota.check_spec(TenantSpec(model="gaussian", generations=5))
    assert exc_info.value.retry_after_s is None  # -> HTTP 400, not 429

    tight = TenantQuota(max_chip_seconds=0.5)
    with pytest.raises(AdmissionRejectedError) as exc_info:
        tight.check_spec(TenantSpec(model="gaussian", generations=8,
                                    population_size=4000))
    assert exc_info.value.retry_after_s is None
    assert "chip-seconds" in exc_info.value.reason


def test_quota_remaining_clamps_at_zero():
    quota = TenantQuota(max_chip_seconds=10.0, max_bytes_on_disk=100,
                        max_generations=4)
    rem = quota.remaining(chip_s=12.0, bytes_on_disk=40,
                          generations_done=1)
    assert rem == {"chip_seconds": 0.0, "bytes_on_disk": 60,
                   "generations": 3}
    unlimited = TenantQuota().remaining(
        chip_s=1e9, bytes_on_disk=10**12, generations_done=10**6)
    assert all(v is None for v in unlimited.values())


def test_retention_policy_validates_keep_last_k():
    with pytest.raises(ValueError):
        RetentionPolicy(keep_last_k=0)
    RetentionPolicy(keep_last_k=1)  # the floor: the resume seam


# ================================================================ sweep
def test_sweep_keep_last_k_prunes_idle_not_running(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(policy=RetentionPolicy(keep_last_k=1),
                            clock=clock)
    idle = FakeTenant(tmp_path, "idle", state="completed")
    busy = FakeTenant(tmp_path, "busy", state="running")
    res = life.sweep([idle, busy])
    assert res["pruned"] == 3 and res["disposed"] == []
    h = History(idle.db_path)
    assert h.n_populations == 1 and h.max_t == 3
    h.close()
    h = History(busy.db_path)
    assert h.n_populations == 4  # RUNNING: writer owns the file
    h.close()
    assert life.generations_gced_total == 3
    assert any(e["kind"] == "generations_gced" for e in idle.events)


def test_sweep_byte_quota_sheds_to_newest_generation_floor(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(
        quota=TenantQuota(max_bytes_on_disk=1),  # impossible: shed all
        clock=clock)
    t = FakeTenant(tmp_path, "fat", state="completed", gens=5)
    life.sweep([t])
    h = History(t.db_path)
    # the newest generation is the floor — never GC'd below it
    assert h.n_populations == 1 and h.max_t == 4
    h.close()


def test_sweep_ttl_disposes_terminal_after_deadline(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(policy=RetentionPolicy(ttl_s=100.0),
                            clock=clock)
    t = FakeTenant(tmp_path, "old", state="completed",
                   finished_at=clock.now())
    sql_path, _, _ = archive_paths(t.db_path)
    clock.advance(99.0)
    assert life.sweep([t])["disposed"] == []
    assert sql_path.is_file()
    clock.advance(2.0)
    assert life.sweep([t])["disposed"] == ["old"]
    assert t.disposed and not sql_path.exists()
    # disposed tenants are terminal for the sweep: never re-disposed
    assert life.sweep([t])["disposed"] == []


def test_sweep_fleet_budget_disposes_oldest_finished(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(
        policy=RetentionPolicy(total_bytes_budget=1), clock=clock)
    older = FakeTenant(tmp_path, "older", state="completed",
                       finished_at=1.0)
    newer = FakeTenant(tmp_path, "newer", state="completed",
                       finished_at=2.0)
    live = FakeTenant(tmp_path, "live", state="running",
                      finished_at=None)
    res = life.sweep([newer, older, live])
    # oldest-finished first; the RUNNING tenant is untouchable
    assert res["disposed"][0] == "older"
    assert "live" not in res["disposed"]
    assert archive_paths(live.db_path)[0].is_file()


def test_dispose_archives_terminal_when_policy_asks(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(
        policy=RetentionPolicy(archive_on_complete=True), clock=clock)
    t = FakeTenant(tmp_path, "keepsake", state="completed")
    Path(t.checkpoint_path).write_bytes(b"ck")
    freed = life.dispose(t)
    sql_path, _, archive = archive_paths(t.db_path)
    assert archive.is_file() and not sql_path.exists()
    assert not os.path.exists(t.checkpoint_path)
    assert t.disposed and life.archives_total == 1
    assert isinstance(freed, int)
    # restorable: the archive is a real backup, not a tombstone
    restore_tenant_db(t.db_path)
    assert _distributions(t.db_path)


def test_gc_skips_never_started_tenant(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(policy=RetentionPolicy(keep_last_k=1),
                            clock=clock)
    ghost = FakeTenant(tmp_path, "ghost", state="queued", gens=0)
    assert life.sweep([ghost])["pruned"] == 0
    # CRITICAL: GC must not CREATE a db for a tenant that never ran
    assert not archive_paths(ghost.db_path)[0].exists()


def test_disk_usage_counts_db_and_columnar(tmp_path, store_scheme):
    db = f"{store_scheme}:///{tmp_path}/t.db"
    _make_history(db, gens=2)
    usage = disk_usage(db)
    assert usage["db"] > 0
    if "columnar" in store_scheme:
        assert usage["columnar"] > 0
    assert usage["total"] == (usage["db"] + usage["columnar"]
                              + usage["archive"])


def test_archive_gating_without_pyarrow_row_store_roundtrips(tmp_path):
    """The archive path never imports pyarrow for a row-store tenant —
    proven under the PYABC_TPU_BLOCK_PYARROW CI leg by this test running
    there (tar + sqlite only)."""
    db = f"sqlite:///{tmp_path}/t.db"
    _make_history(db, gens=2)
    archive_tenant_db(db)
    restore_tenant_db(db)
    assert len(_distributions(db)) == 2


@pytest.mark.skipif(not has_pyarrow(), reason="needs pyarrow")
def test_lifecycle_manager_bytes_on_disk_gauges_tenant_registry(tmp_path):
    clock = VirtualClock()
    life = LifecycleManager(clock=clock)
    t = FakeTenant(tmp_path, "gauged", scheme="sqlite+columnar")
    total = life.bytes_on_disk(t)
    assert total > 0 and t.bytes_on_disk == total
