"""Learned summary statistics on the fused multi-generation path.

PredictorSumstat (Fearnhead-Prangle) rides the fused chunks as constant
device params; the predictor refits on the host BETWEEN chunks and the
next chunk is dispatched off a fresh carry (transition-params pattern).
Adaptive scale weights are reduced in the TRANSFORMED feature space
inside the kernel.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt

NOISE_SD = 0.3
POST_MU = 1.0 * (2 / NOISE_SD**2) / (1.0 + 2 / NOISE_SD**2)


def _fp_model():
    @pt.JaxModel.from_function(["theta"], name="fp")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        sig = theta[0] + NOISE_SD * jax.random.normal(k1, (2,))
        noise = 5.0 * jax.random.normal(k2, (4,))
        return {"sig": sig, "noise": noise}

    return model


def _run(distance, seed, fused_generations, n_gens=8):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(_fp_model(), prior, distance, population_size=400,
                    eps=pt.MedianEpsilon(), seed=seed,
                    fused_generations=fused_generations)
    obs = {"sig": np.asarray([1.0, 1.0]), "noise": np.zeros(4)}
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=n_gens)
    df, w = h.get_distribution(0, h.max_t)
    return abc, h, float(np.sum(df["theta"] * w))


def _dist():
    return pt.AdaptivePNormDistance(
        p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())
    )


def test_fused_capable_with_predictor_sumstat():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(_fp_model(), prior, _dist(), population_size=100,
                    eps=pt.MedianEpsilon())
    assert abc._fused_chunk_capable()


def test_fused_chunks_taken_and_posterior_matches_unfused():
    # fused (chunks of 3 -> at least one inter-chunk predictor refit)
    abc_f, h_f, mu_f = _run(_dist(), seed=31, fused_generations=3)
    fused_flags = [h_f.get_telemetry(t).get("fused_chunk")
                   for t in range(h_f.n_populations)]
    assert any(fused_flags), f"fused path not taken: {fused_flags}"
    assert h_f.n_populations >= 6
    # the predictor actually refit after the first chunk
    assert abc_f.distance_function.sumstat._last_fit_t is not None
    assert abc_f.distance_function.sumstat._last_fit_t >= 4

    # unfused reference (per-generation pipelined loop)
    _, h_u, mu_u = _run(_dist(), seed=31, fused_generations=1)
    assert abs(mu_f - POST_MU) < 0.25
    assert abs(mu_u - POST_MU) < 0.25
    # both estimates agree with each other statistically
    assert abs(mu_f - mu_u) < 0.3


def test_fused_plain_pnorm_with_sumstat():
    _, h, mu = _run(
        pt.PNormDistance(p=2,
                         sumstat=pt.PredictorSumstat(pt.LinearPredictor())),
        seed=33, fused_generations=4)
    fused_flags = [h.get_telemetry(t).get("fused_chunk")
                   for t in range(h.n_populations)]
    assert any(fused_flags)
    assert abs(mu - POST_MU) < 0.25
