"""Stub-binary contract tests for the R / Julia / COPASI adapters.

The fake-qsub pattern of ``test_sge.py`` applied to the remaining gated
adapters (VERDICT r2 weak #5): a fake ``Rscript`` / ``julia`` on PATH
reads the generated driver + parameter files and writes outputs through
the REAL file contract, so the adapters' execution paths run everywhere;
COPASI's basico API usage is exercised against a recording mock module.
"""
import json
import os
import stat
import sys
import textwrap
import types

import numpy as np
import pandas as pd
import pytest

import pyabc_tpu as pt

RSCRIPT_STUB = textwrap.dedent("""\
    #!{python}
    import csv, sys
    args = sys.argv[1:]  # driver, user_script, fn/name, [fin], fout
    driver = open(args[0]).read()
    assert "commandArgs" in driver, driver
    if len(args) == 5:
        assert "read.csv" in driver, driver
    assert open(args[1]).read().startswith("# user R script")
    if len(args) == 5:
        _, _, fn, fin, fout = args
        assert fn == "myModel", fn
        rows = list(csv.reader(open(fin)))
        pars = dict(zip(rows[0], (float(v) for v in rows[1])))
        with open(fout, "w") as fh:
            fh.write("x\\n%r\\n" % (pars["theta"] * 2.0))
    else:
        _, _, name, fout = args
        assert name == "mySumStatData", name
        with open(fout, "w") as fh:
            fh.write("x\\n1.5\\n")
""")

JULIA_STUB = textwrap.dedent("""\
    #!{python}
    import json, sys
    driver, script, fn, fin, fout = sys.argv[1:]
    assert "JSON.parsefile" in open(driver).read()
    assert open(script).read().startswith("# user julia script")
    assert fn == "mymodel", fn
    pars = json.load(open(fin))
    json.dump({{"x": pars["theta"] * 3.0}}, open(fout, "w"))
""")


def _install(bindir, name, content):
    p = bindir / name
    p.write_text(content.format(python=sys.executable))
    p.chmod(p.stat().st_mode | stat.S_IEXEC)


@pytest.fixture
def fake_binaries(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    _install(bindir, "Rscript", RSCRIPT_STUB)
    _install(bindir, "julia", JULIA_STUB)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return tmp_path


class TestRAdapter:
    def test_model_and_observation_contract(self, fake_binaries):
        from pyabc_tpu.external import R

        script = fake_binaries / "user.R"
        script.write_text("# user R script\n")
        r = R(str(script))
        model = r.model("myModel")
        out = model.sample({"theta": 2.5})
        np.testing.assert_allclose(out["x"], [5.0])
        obs = r.observation("mySumStatData")
        np.testing.assert_allclose(obs["x"], [1.5])

    def test_model_in_abc_loop(self, fake_binaries):
        from pyabc_tpu.external import R

        script = fake_binaries / "user.R"
        script.write_text("# user R script\n")
        model = R(str(script)).model("myModel")
        prior = pt.Distribution(theta=pt.RV("uniform", 0.0, 2.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=10,
                        eps=pt.ListEpsilon([1.0]),
                        sampler=pt.SingleCoreSampler(), seed=1)
        abc.new("sqlite://", {"x": 2.0})
        h = abc.run(max_nr_populations=1)
        assert h.n_populations == 1
        df, w = h.get_distribution(0, h.max_t)
        # x = 2*theta, obs 2.0, eps 1.0 -> theta in [0.5, 1.5]
        assert abs(float(np.sum(df["theta"] * w)) - 1.0) < 0.5


class TestJuliaAdapter:
    def test_model_contract(self, fake_binaries):
        from pyabc_tpu.external import JuliaModel

        script = fake_binaries / "user.jl"
        script.write_text("# user julia script\n")
        model = JuliaModel(str(script), "mymodel")
        out = model.sample({"theta": 2.0})
        np.testing.assert_allclose(out["x"], 6.0)


def _mock_basico(calls, *, as_global=False, with_param=True):
    mod = types.ModuleType("basico")

    def load_model(path):
        calls.append(("load_model", path))
        return "DM"

    def get_parameters(key, model=None):
        calls.append(("get_parameters", key))
        return pd.DataFrame({"value": [1.0]}) if (with_param and
                                                  not as_global) else None

    def set_parameters(key, initial_value=None, model=None):
        calls.append(("set_parameters", key, initial_value))

    def get_global_quantities(key, model=None):
        calls.append(("get_global_quantities", key))
        return pd.DataFrame({"value": [1.0]}) if (with_param and
                                                  as_global) else None

    def set_global_quantities(key, initial_value=None, model=None):
        calls.append(("set_global_quantities", key, initial_value))

    def run_time_course(duration=None, intervals=None, method=None,
                        model=None):
        calls.append(("run_time_course", duration, intervals, method))
        return pd.DataFrame({"A": np.linspace(0, 1, intervals + 1)})

    def remove_datamodel(dm):
        calls.append(("remove_datamodel", dm))

    for fn in (load_model, get_parameters, set_parameters,
               get_global_quantities, set_global_quantities,
               run_time_course, remove_datamodel):
        setattr(mod, fn.__name__, fn)
    return mod


class TestCopasiAdapter:
    def _model(self, monkeypatch, calls, **kwargs):
        monkeypatch.setitem(
            sys.modules, "basico", _mock_basico(calls, **kwargs))
        from pyabc_tpu.copasi import BasicoModel

        return BasicoModel("model.cps", duration=10.0, n_points=5)

    def test_reaction_parameter_call_sequence(self, monkeypatch):
        calls = []
        model = self._model(monkeypatch, calls)
        out = model.sample({"k1": 0.7})
        assert out["A"].shape == (5,)
        assert ("set_parameters", "k1", 0.7) in calls
        assert ("run_time_course", 10.0, 4, "deterministic") in calls
        assert calls[-1] == ("remove_datamodel", "DM")
        # both parameter classes are probed (COPASI exposes tunables as
        # reaction parameters OR global quantities)
        assert ("get_parameters", "k1") in calls
        assert ("get_global_quantities", "k1") in calls

    def test_global_quantity_fallback(self, monkeypatch):
        calls = []
        model = self._model(monkeypatch, calls, as_global=True)
        model.sample({"kG": 0.3})
        assert ("set_global_quantities", "kG", 0.3) in calls
        assert not any(c[0] == "set_parameters" for c in calls)

    def test_unknown_parameter_raises_and_cleans_up(self, monkeypatch):
        calls = []
        model = self._model(monkeypatch, calls, with_param=False)
        with pytest.raises(KeyError, match="neither"):
            model.sample({"nope": 1.0})
        assert calls[-1] == ("remove_datamodel", "DM")
