"""Fused (multi-generation on-device) noisy ABC.

The stochastic acceptor + Temperature configs now ride the fused chunk
loop: pdf-norm recursion, temperature schemes (including the
AcceptanceRateScheme with the reference's record reweighting by
transition_pd / transition_pd_prev) and the stochastic accept/weight all
run inside the multigen kernel. These tests pin (a) capability detection,
(b) the reference temperature math on host and device, (c) fused-vs-unfused
posterior parity, (d) the record reweighting itself.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.epsilon.temperature import (
    AcceptanceRateScheme,
    DalyScheme,
    ExpDecayFixedIterScheme,
)

NOISE_SD = 0.3
PRIOR_SD = 1.0
X_OBS = 0.8


def _det_model():
    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    return model


def _noisy_abc(seed=21, fused_generations=4, pop=400, eps=None, **kwargs):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    return pt.ABCSMC(
        _det_model(), prior,
        pt.IndependentNormalKernel(var=[NOISE_SD**2]),
        population_size=pop,
        eps=eps if eps is not None else pt.Temperature(),
        acceptor=pt.StochasticAcceptor(),
        seed=seed, fused_generations=fused_generations, **kwargs,
    )


def exact_posterior():
    var = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
    return var * X_OBS / NOISE_SD**2, np.sqrt(var)


class TestCapability:
    def test_default_noisy_config_is_fused_capable(self):
        abc = _noisy_abc()
        abc.new("sqlite://", {"x": X_OBS})
        abc._initialize_components(8)
        assert abc._fused_chunk_capable()

    def test_daly_and_ess_schemes_are_fused_capable(self):
        from pyabc_tpu.epsilon.temperature import EssScheme

        for scheme in (DalyScheme(), EssScheme()):
            abc = _noisy_abc(eps=pt.Temperature(schemes=[scheme]))
            abc.new("sqlite://", {"x": X_OBS})
            abc._initialize_components(8)
            assert abc._fused_chunk_capable(), scheme

    def test_log_file_falls_back(self):
        abc = _noisy_abc()
        abc.acceptor.log_file = "/tmp/nope.json"
        abc.new("sqlite://", {"x": X_OBS})
        abc._initialize_components(8)
        assert not abc._fused_chunk_capable()


class TestDeterministicLadderParity:
    """With a deterministic scheme, the fused device temperature trajectory
    must reproduce the reference recursion exactly (up to f32)."""

    def _run(self, fused_generations):
        abc = _noisy_abc(
            seed=7, fused_generations=fused_generations, pop=300,
            eps=pt.Temperature(schemes=[ExpDecayFixedIterScheme()],
                               initial_temperature=64.0),
        )
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=7)
        return abc, h

    def test_fused_trajectory_matches_reference_recursion(self):
        abc, h = self._run(4)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        # reference: T_{t+1} = T_t ** ((n-t-1-1+1)/(n-t-1)); final gen T=1
        T, n = 64.0, 7
        expected = {0: 64.0}
        for t in range(1, n):
            t_to_go = n - t
            T = 1.0 if t_to_go <= 1 else T ** ((t_to_go - 1) / t_to_go)
            expected[t] = T
        for t, exp_T in expected.items():
            if t in abc.eps.temperatures:
                assert abc.eps.temperatures[t] == pytest.approx(
                    exp_T, rel=1e-3
                ), f"t={t}"

    def test_fused_posterior_matches_unfused(self):
        _, h_f = self._run(4)
        _, h_u = self._run(1)
        mu_true, sd_true = exact_posterior()
        for h in (h_f, h_u):
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
            assert mu == pytest.approx(mu_true, abs=0.15)
            assert sd == pytest.approx(sd_true, abs=0.12)


class TestDalyFused:
    """DalyScheme's contraction state k rides the chunk carry; away from
    acceptance collapse the recursion is deterministic: k_t = alpha *
    min(k_{t-1}, T_{t-1}), T_t = max(1, T_{t-1} - k_t) -> T_t = T_0/2^t
    for alpha = 0.5 and T_0 = k_0."""

    def _run(self, fused_generations):
        abc = _noisy_abc(
            seed=11, fused_generations=fused_generations, pop=300,
            eps=pt.Temperature(schemes=[DalyScheme()],
                               initial_temperature=64.0),
        )
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=7)
        return abc, h

    def test_fused_trajectory_matches_reference_recursion(self):
        abc, h = self._run(4)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        for t in range(min(6, h.n_populations)):
            if t in abc.eps.temperatures:
                assert abc.eps.temperatures[t] == pytest.approx(
                    max(1.0, 64.0 / 2**t), rel=1e-3
                ), f"t={t}"
        # the host scheme state mirrors the device carry (resume safety)
        sch = abc.eps.schemes[0]
        assert sch._k, "host DalyScheme._k never mirrored from device"

    def test_fused_posterior_matches_unfused(self):
        _, h_f = self._run(4)
        _, h_u = self._run(1)
        mu_true, sd_true = exact_posterior()
        for h in (h_f, h_u):
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            assert mu == pytest.approx(mu_true, abs=0.15)


class TestEssFused:
    def test_fused_posterior_and_monotone_trajectory(self):
        from pyabc_tpu.epsilon.temperature import EssScheme

        abc = _noisy_abc(
            seed=13, fused_generations=4, pop=400,
            eps=pt.Temperature(schemes=[EssScheme()],
                               initial_temperature=64.0),
        )
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=8)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        temps = [abc.eps.temperatures[t] for t in sorted(abc.eps.temperatures)]
        assert all(b <= a + 1e-6 for a, b in zip(temps, temps[1:]))
        assert temps[-1] == pytest.approx(1.0)
        mu_true, _ = exact_posterior()
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(mu_true, abs=0.15)

    def test_ess_device_bisection_matches_host_scheme(self):
        """Same weighted distances -> the in-kernel bisection and the host
        EssScheme must agree on the proposed temperature."""
        import pandas as pd

        from pyabc_tpu.epsilon.temperature import EssScheme

        rng = np.random.default_rng(0)
        vals = -np.abs(rng.normal(3.0, 2.0, 200))  # log kernel values
        w = rng.uniform(0.2, 1.0, 200)
        w = w / w.sum()
        host = EssScheme(target_relative_ess=0.6)
        t_host = host(
            2,
            get_weighted_distances=lambda: pd.DataFrame(
                {"distance": vals, "w": w}),
            prev_temperature=50.0,
        )

        import jax.numpy as jnp

        from pyabc_tpu.inference.util import DeviceContext

        ctx = object.__new__(DeviceContext)  # stateless: method needs no init
        temp = jnp.asarray(50.0, jnp.float32)
        t_dev = float(
            DeviceContext._stochastic_gen_update(
                ctx,
                ((("ess", 0.6),), -1, None, False),
                None, None,
                {"theta": None, "logq": None, "valid": None,
                 "distance": None},
                {"distance": jnp.asarray(vals, jnp.float32)},
                jnp.ones(200, bool),
                jnp.asarray(w, jnp.float32),
                jnp.zeros(()), jnp.asarray(-1e30), jnp.zeros(()),
                temp, jnp.asarray(0.5), jnp.asarray(2),
            )[0]
        )
        assert t_dev == pytest.approx(t_host, rel=5e-3)


class TestFusedDefaultTemperature:
    def test_posterior_and_mirrored_state(self):
        abc = _noisy_abc(seed=3, fused_generations=4, pop=500)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=10)
        mu_true, sd_true = exact_posterior()
        df, w = h.get_distribution(0, h.max_t)
        mu = float(np.sum(df["theta"] * w))
        assert mu == pytest.approx(mu_true, abs=0.15)
        # final generation runs at T = 1 (exact posterior convention)
        assert abc.eps(h.max_t) == pytest.approx(1.0)
        # host mirrors of the device recursions exist for every generation
        for t in range(h.n_populations):
            assert t in abc.eps.temperatures
            assert t in abc.acceptor.pdf_norms


class TestAcceptanceRateReweighting:
    def test_reweighted_bisection_closed_form(self):
        """Two records: one at the norm (rate 1), one 10 nats below.
        With all weight on the second, T solves exp(-10/T) = target."""
        import pandas as pd

        scheme = AcceptanceRateScheme(target_rate=0.3)

        def records(w1, w2):
            return pd.DataFrame({
                "distance": [0.0, -10.0],
                "accepted": [True, False],
                "transition_pd_prev": [1.0, 1.0],
                "transition_pd": [w1, w2],
            })

        t_all_first = scheme(
            1, get_all_records=lambda: records(1.0, 0.0), pdf_norm=0.0,
        )
        assert t_all_first == pytest.approx(1.0)
        t_all_second = scheme(
            1, get_all_records=lambda: records(0.0, 1.0), pdf_norm=0.0,
        )
        assert t_all_second == pytest.approx(-10.0 / np.log(0.3), rel=1e-3)

    def test_host_records_carry_proposal_density(self):
        """SingleCoreSampler + Temperature: records must carry finite
        proposal densities so the provider adds the reweighting columns."""
        abc = _noisy_abc(seed=5, pop=60,
                         sampler=pt.SingleCoreSampler())
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations >= 2

    def test_capped_retention_keeps_proposal_arrays_aligned(self):
        """finite max_nr_recorded_particles trims accepted-first; the
        proposal arrays must follow the same retention (they feed the same
        DataFrame as the distances)."""
        abc = _noisy_abc(seed=5, pop=100, fused_generations=1,
                         max_nr_recorded_particles=150)
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=3)
        assert h.n_populations >= 2

    def test_device_records_carry_proposal_density(self):
        """BatchedSampler unfused noisy generation: the record ring ships
        (m, theta, logq) and the Sample exposes proposal densities."""
        abc = _noisy_abc(seed=5, pop=200, fused_generations=1)
        abc.new("sqlite://", {"x": X_OBS})
        abc._initialize_components(5)
        abc.distance_function.configure_sampler(abc.sampler)
        abc.eps.configure_sampler(abc.sampler)
        spec = abc._generation_spec(0)
        sample = abc.sampler.sample_until_n_accepted(200, spec, 0)
        assert sample.all_proposal_pds is not None
        assert np.isfinite(sample.all_proposal_pds).all()
        assert (sample.all_proposal_pds > 0).all()
        assert sample.all_thetas.shape[1] == 1
        # prior-mode records: proposal density == prior pdf
        import scipy.stats as st

        expect = st.norm(0.0, PRIOR_SD).pdf(sample.all_thetas[:, 0])
        np.testing.assert_allclose(
            sample.all_proposal_pds, expect, rtol=2e-3
        )


class TestListTemperatureFused:
    """ListTemperature is a deterministic ladder: it rides the chunk's
    eps_fixed input (like ListEpsilon), with only the pdf-norm recursion
    carried on device."""

    def _run(self, fused_generations):
        ladder = [32.0, 16.0, 8.0, 4.0, 2.0, 1.0]
        abc = _noisy_abc(
            seed=23, fused_generations=fused_generations, pop=300,
            eps=pt.ListTemperature(ladder),
        )
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=len(ladder))
        return abc, h, ladder

    def test_capable_and_ladder_respected(self):
        abc, h, ladder = self._run(4)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        eps_used = h.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        np.testing.assert_allclose(eps_used, ladder[: len(eps_used)])
        # the constructor-built ladder dict must survive the device mirror
        # (chunk-clamped eps_next values must NOT clobber it)
        assert abc.eps.temperatures == dict(enumerate(ladder))

    def test_fused_posterior_matches_unfused(self):
        _, h_f, _ = self._run(4)
        _, h_u, _ = self._run(1)
        mu_true, sd_true = exact_posterior()
        for h in (h_f, h_u):
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            assert mu == pytest.approx(mu_true, abs=0.15)


class TestScaledPDFNormFused:
    def test_capable_and_posterior_parity(self):
        """ScaledPDFNorm (down-scale the norm when acceptance would
        collapse) now has an in-kernel twin; fused and unfused runs must
        agree with the exact posterior, and the host pdf_norms mirror the
        device recursion."""
        from pyabc_tpu.acceptor.pdf_norm import ScaledPDFNorm

        def make(fused_generations):
            # a forced decay ladder keeps T > 1 for several generations
            # (with the scaled norm, acceptance-rate-driven schedules hit
            # T=1 immediately — correct host semantics, but then nothing
            # would exercise the in-kernel scaled-norm recursion)
            prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
            return pt.ABCSMC(
                _det_model(), prior,
                pt.IndependentNormalKernel(var=[NOISE_SD**2]),
                population_size=300,
                eps=pt.Temperature(schemes=[ExpDecayFixedIterScheme()],
                                   initial_temperature=64.0),
                acceptor=pt.StochasticAcceptor(
                    pdf_norm_method=ScaledPDFNorm(factor=5.0, alpha=0.5)),
                seed=29, fused_generations=fused_generations,
            )

        abc_f = make(4)
        abc_f.new("sqlite://", {"x": X_OBS})
        h_f = abc_f.run(max_nr_populations=7)
        assert h_f.get_telemetry(2).get("fused_chunk"), "not fused"
        abc_u = make(1)
        abc_u.new("sqlite://", {"x": X_OBS})
        h_u = abc_u.run(max_nr_populations=7)
        mu_true, _ = exact_posterior()
        for h in (h_f, h_u):
            df, w = h.get_distribution(0, h.max_t)
            mu = float(np.sum(df["theta"] * w))
            assert mu == pytest.approx(mu_true, abs=0.15)
        # scaled norms mirrored for every fused generation
        for t in range(1, h_f.n_populations):
            assert t in abc_f.acceptor.pdf_norms

    def test_device_scaled_norm_matches_host_method(self):
        """Same accepted kernel values -> the in-kernel quantile cap must
        equal the host ScaledPDFNorm (np.quantile linear interpolation)."""
        import jax.numpy as jnp

        from pyabc_tpu.acceptor.pdf_norm import ScaledPDFNorm
        from pyabc_tpu.inference.util import DeviceContext

        rng = np.random.default_rng(1)
        vals = -np.abs(rng.normal(2.0, 1.5, 128))
        host = ScaledPDFNorm(factor=5.0, alpha=0.5)
        norm_host = host(kernel_val=vals, pdf_max=None,
                         max_found=float(vals.max()), prev_pdf_norm=-1e30)

        ctx = object.__new__(DeviceContext)
        out = DeviceContext._stochastic_gen_update(
            ctx,
            ((("exp_decay_fixed_ratio", 0.5, 1e-4, 0.5),), -1, None, False,
             (5.0, 0.5)),
            None, None,
            {"theta": None, "logq": None, "valid": None, "distance": None},
            {"distance": jnp.asarray(vals, jnp.float32)},
            jnp.ones(128, bool),
            jnp.full(128, 1 / 128, jnp.float32),
            jnp.asarray(-1e30), jnp.asarray(-1e30), jnp.zeros(()),
            jnp.asarray(50.0, jnp.float32), jnp.asarray(0.5),
            jnp.asarray(2),
        )
        norm_dev = float(out[1][0])
        assert norm_dev == pytest.approx(norm_host, rel=1e-4, abs=1e-4)


class TestCapabilityGates:
    """Configs that must NOT take the fused path (review regressions)."""

    def test_stochastic_local_transition_needs_constant_population(self):
        abc = _noisy_abc(
            transitions=pt.LocalTransition(),
        )
        abc.population_strategy = pt.ListPopulationSize([400] * 8)
        abc.new("sqlite://", {"x": X_OBS})
        abc._initialize_components(8)
        assert not abc._fused_chunk_capable()

    def test_empty_scheme_list_falls_back(self):
        """Temperature(schemes=[]) has no annealing recursion for the
        device to run; it must use the host loop (which applies the
        final-generation T=1 forcing)."""
        abc = _noisy_abc(eps=pt.Temperature(schemes=[],
                                            initial_temperature=64.0))
        abc.new("sqlite://", {"x": X_OBS})
        abc._initialize_components(8)
        assert not abc._fused_chunk_capable()
