"""Multi-chip (mesh) correctness tests on the virtual 8-device CPU platform.

The TPU analog of the reference's multi-node-as-multi-process-on-localhost
testing (SURVEY.md §4): conftest forces
``--xla_force_host_platform_device_count=8``, so a real 8-device
``jax.sharding.Mesh`` exists and GSPMD inserts real cross-device
partitioning — no fake backend.

Covers VERDICT r1 #2: (i) posterior agreement between meshed and
single-device runs, (ii) the compiled kernel actually carries sharded
shapes across devices, (iii) slot-trim determinism across shardings.
"""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import pyabc_tpu as pt
from pyabc_tpu.models import model_selection as msel

pytestmark = pytest.mark.mesh

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _mesh(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} virtual cpu devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), axis_names=("particles",))


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _moments(h, m=0, par="theta"):
    df, w = h.get_distribution(m)
    mu = float(np.sum(df[par] * w))
    sd = float(np.sqrt(np.sum(w * (df[par] - mu) ** 2)))
    return mu, sd


class TestMeshedGaussianToy:
    def test_posterior_agrees_with_single_device(self):
        kwargs = dict(
            population_size=400, eps=pt.ListEpsilon([1.0, 0.5, 0.3]), seed=21
        )
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))

        abc1 = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                         **kwargs)
        abc1.new("sqlite://", {"x": X_OBS})
        h1 = abc1.run(max_nr_populations=3)
        mu1, sd1 = _moments(h1)

        abc8 = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                         mesh=_mesh(), **kwargs)
        assert isinstance(abc8.sampler, pt.BatchedSampler)
        abc8.new("sqlite://", {"x": X_OBS})
        h8 = abc8.run(max_nr_populations=3)
        mu8, sd8 = _moments(h8)

        assert mu8 == pytest.approx(POST_MU, abs=0.2)
        assert mu8 == pytest.approx(mu1, abs=0.2)
        assert sd8 == pytest.approx(sd1, abs=0.15)

    def test_multimodel_on_mesh(self):
        models, priors, analytic = msel.tractable_pair()
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=600, eps=pt.MedianEpsilon(),
                        seed=22, mesh=_mesh())
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        probs = h.get_model_probabilities(h.max_t)
        expected = analytic(X_OBS)
        for m in range(2):
            p = float(probs["p"].get(m, 0.0))
            assert p == pytest.approx(expected[m], abs=0.18), (m, p, expected)


class TestShardingMechanics:
    """The kernel must genuinely shard over the mesh, not replicate."""

    def _ctx(self, mesh):
        from pyabc_tpu.inference.util import DeviceContext

        model = _gauss_model()
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        obs = {"x": np.asarray(X_OBS)}
        spec = pt.SumStatSpec(obs)
        distance = pt.PNormDistance(p=2, sumstat_spec=spec)
        distance.initialize(0, None, obs)
        return DeviceContext(
            models=[model], parameter_priors=[prior],
            model_prior_logits=np.asarray([0.0]),
            distance=distance, acceptor=pt.UniformAcceptor(), spec=spec,
            x_0_flat=np.asarray(spec.flatten(obs)),
            transition_cls=pt.MultivariateNormalTransition, mesh=mesh,
        )

    def test_round_outputs_sharded_over_devices(self):
        mesh = _mesh()
        ctx = self._ctx(mesh)
        _, dyn = ctx.build_dyn_args(t=0, eps_value=1.0)
        B = 64
        out = ctx.round_kernel(B, "prior")(jax.random.key(0), dyn)
        sh = out["theta"].sharding
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P("particles")
        assert len(sh.mesh.devices.ravel()) == 8
        # each device holds exactly B/8 lanes, not a replica of all B
        shard_shapes = {s.data.shape for s in out["theta"].addressable_shards}
        assert shard_shapes == {(B // 8, 1)}
        assert len(out["theta"].addressable_shards) == 8

    def test_slot_trim_deterministic_across_shardings(self):
        """Same key => identical accepted set with and without the mesh:
        the slot-ordered compaction is sharding-invariant (the reference's
        dynamic-scheduler unbiasedness invariant, SURVEY.md §3.4)."""
        key = jax.random.key(42)
        results = []
        for mesh in (None, _mesh()):
            ctx = self._ctx(mesh)
            _, dyn = ctx.build_dyn_args(t=0, eps_value=0.8)
            out = ctx.run_generation(
                key, 64, "prior", dyn, n_cap=32, rec_cap=64, max_rounds=16
            )
            results.append(out)
        a, b = results
        assert a["n_acc"] == b["n_acc"]
        np.testing.assert_array_equal(a["slot"], b["slot"])
        np.testing.assert_allclose(a["theta"], b["theta"], rtol=1e-5)
        np.testing.assert_allclose(
            a["log_weight"], b["log_weight"], rtol=1e-5
        )


class TestMeshedFusedChunks:
    """The fused multi-generation loop on an 8-device mesh: multiple chunks
    with on-device adaptation must shard and agree with the unmeshed run."""

    def test_fused_chunks_on_mesh_agree_with_single_device(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        results = {}
        for name, mesh in (("single", None), ("mesh", _mesh())):
            abc = pt.ABCSMC(_gauss_model(), prior,
                            pt.AdaptivePNormDistance(p=2),
                            population_size=400, eps=pt.MedianEpsilon(),
                            seed=23, mesh=mesh, fused_generations=3)
            assert abc._fused_chunk_capable()
            abc.new("sqlite://", {"x": X_OBS})
            h = abc.run(max_nr_populations=7)  # gen0 + 2 fused chunks
            assert h.n_populations == 7
            assert h.get_telemetry(5).get("chunk_index") == 2
            results[name] = _moments(h)
        mu_s, sd_s = results["single"]
        mu_m, sd_m = results["mesh"]
        assert mu_m == pytest.approx(POST_MU, abs=0.25)
        assert mu_m == pytest.approx(mu_s, abs=0.2)
        assert sd_m == pytest.approx(sd_s, abs=0.15)

    @pytest.mark.slow
    def test_fused_chunk_large_population_on_mesh(self):
        """Round-4 verdict Weak #5: nothing exercised sharded collectives
        at a realistic population. Pop 2048 with a G=4 fused chunk on the
        8-device mesh — (B >= 4096, n_cap 2048) sharded shapes, in-kernel
        adaptive-distance reweighting and transition refit — must agree
        with the single-device run on the posterior."""
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        results = {}
        for name, mesh in (("single", None), ("mesh", _mesh())):
            abc = pt.ABCSMC(_gauss_model(), prior,
                            pt.AdaptivePNormDistance(p=2),
                            population_size=2048, eps=pt.MedianEpsilon(),
                            seed=29, mesh=mesh, fused_generations=4)
            assert abc._fused_chunk_capable()
            abc.new("sqlite://", {"x": X_OBS}, store_sum_stats=False)
            h = abc.run(max_nr_populations=5)  # gen0 + one G=4 chunk
            assert h.n_populations == 5
            assert h.get_telemetry(3).get("fused_chunk") == 4
            counts = h.get_nr_particles_per_population()
            assert all(counts[t] == 2048 for t in range(5))
            results[name] = _moments(h)
        mu_s, sd_s = results["single"]
        mu_m, sd_m = results["mesh"]
        assert mu_m == pytest.approx(POST_MU, abs=0.15)
        assert mu_m == pytest.approx(mu_s, abs=0.1)
        assert sd_m == pytest.approx(sd_s, abs=0.08)
