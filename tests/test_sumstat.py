"""Sumstat/predictor layer tests (Fearnhead-Prangle learned statistics).

Mirrors the reference's sumstat/predictor suites (SURVEY.md §2.2 last row):
predictor regression sanity on synthetic data, identity trafos, and the
headline integration test — learned statistics beat identity statistics on
posterior error when the raw output contains noise dimensions.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt


class TestPredictors:
    @pytest.fixture
    def xy(self, rng):
        x = rng.normal(size=(400, 6))
        W = rng.normal(size=(6, 2))
        y = x @ W + 0.05 * rng.normal(size=(400, 2))
        return x, y, W

    @pytest.mark.parametrize("cls,kwargs", [
        (pt.LinearPredictor, {}),
        (pt.LassoPredictor, {"alpha": 1e-4}),
        (pt.MLPPredictor, {"n_steps": 300}),
        (pt.GPPredictor, {"cap": 256}),
    ])
    def test_fit_predict_recovers_signal(self, xy, cls, kwargs):
        x, y, _ = xy
        p = cls(**kwargs)
        p.fit(x[:300], y[:300])
        assert p.fitted
        pred = p.predict(x[300:])
        resid = np.mean((pred - y[300:]) ** 2)
        base = np.mean((y[300:] - y[:300].mean(0)) ** 2)
        assert resid < 0.25 * base  # strongly better than the mean predictor

    @pytest.mark.parametrize("cls,kwargs", [
        (pt.LinearPredictor, {}),
        (pt.MLPPredictor, {"n_steps": 100}),
        (pt.GPPredictor, {"cap": 128}),
    ])
    def test_device_predict_matches_host(self, xy, cls, kwargs):
        x, y, _ = xy
        p = cls(**kwargs)
        p.fit(x, y)
        params = p.device_params()
        dev = jax.jit(lambda v: p.device_predict(v, params))(
            np.asarray(x[0], np.float32)
        )
        np.testing.assert_allclose(
            np.asarray(dev), p.predict(x[0]), rtol=1e-3, atol=1e-3
        )

    def test_model_selection_picks_better(self, xy):
        x, y, _ = xy
        ms = pt.ModelSelectionPredictor([
            pt.LinearPredictor(), pt.GPPredictor(cap=64)
        ])
        ms.fit(x, y)
        assert ms.fitted
        assert ms.chosen is not None


class TestIdentitySumstat:
    def test_trafos_expand_features(self):
        ss = pt.IdentitySumstat(trafos=[lambda v: v, lambda v: v**2])
        flat = np.asarray([1.0, 2.0, 3.0])
        out = ss(flat)
        np.testing.assert_allclose(out, [1, 2, 3, 1, 4, 9])
        assert ss.out_dim(3) == 6

    def test_device_fn_matches_host(self):
        ss = pt.IdentitySumstat(trafos=[lambda v: v, lambda v: v**2])
        spec = pt.SumStatSpec({"a": np.zeros(3)})
        fn = jax.jit(lambda x: ss.device_fn(spec)(x, ()))
        x = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(np.asarray(fn(x)), ss(x), rtol=1e-6)


NOISE_SD = 0.3


def _fp_model():
    """2 informative dims + 4 pure-noise dims: identity p-norm distance is
    diluted by noise; learned stats ignore it (the Fearnhead-Prangle toy)."""

    @pt.JaxModel.from_function(["theta"], name="fp")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        sig = theta[0] + NOISE_SD * jax.random.normal(k1, (2,))
        noise = 5.0 * jax.random.normal(k2, (4,))
        return {"sig": sig, "noise": noise}

    return model


def _run_fp(distance, seed):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(_fp_model(), prior, distance, population_size=400,
                    eps=pt.MedianEpsilon(), seed=seed)
    assert abc._device_capable
    obs = {"sig": np.asarray([1.0, 1.0]), "noise": np.zeros(4)}
    abc.new("sqlite://", obs)
    h = abc.run(max_nr_populations=6)
    df, w = h.get_distribution(0)
    return float(np.sum(df["theta"] * w))


class TestFearnheadPrangleIntegration:
    @pytest.mark.slow
    def test_learned_stats_beat_identity(self):
        # true posterior concentrates near theta = 1 (2 obs of mean theta)
        post_mu = 1.0 * (2 / NOISE_SD**2) / (1.0 + 2 / NOISE_SD**2)
        err_learned = []
        err_identity = []
        for seed in (101, 102):
            mu_l = _run_fp(pt.PNormDistance(
                p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())
            ), seed)
            # UNWEIGHTED identity p-norm: the 4 noise dims (sd 5.0 vs signal
            # sd 0.3) dominate the distance and wreck the posterior — this
            # is the regime Fearnhead-Prangle statistics are for. (Adaptive
            # scale weights also fix this toy, which is why the baseline
            # here is the plain PNormDistance.)
            mu_i = _run_fp(pt.PNormDistance(p=2), seed)
            err_learned.append(abs(mu_l - post_mu))
            err_identity.append(abs(mu_i - post_mu))
        assert np.mean(err_learned) < np.mean(err_identity)
        assert np.mean(err_learned) < 0.25

    def test_learned_stats_with_adaptive_distance(self):
        """PredictorSumstat composes with adaptive scale reweighting."""
        post_mu = 1.0 * (2 / NOISE_SD**2) / (1.0 + 2 / NOISE_SD**2)
        mu = _run_fp(pt.AdaptivePNormDistance(
            p=2, sumstat=pt.PredictorSumstat(pt.LinearPredictor())
        ), seed=103)
        assert abs(mu - post_mu) < 0.25
