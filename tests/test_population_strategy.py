"""AdaptivePopulationSize & bootstrap-CV machinery.

Reference parity: ``pyabc/populationstrategy.py::AdaptivePopulationSize``
and ``pyabc/cv/bootstrap.py::calc_cv`` (SURVEY.md §2.1 Population-size row).
Covers the closed-form weighting of ``calc_cv``, the statistical behavior
of ``Transition.mean_cv`` under bootstrap resampling, the bisection of
``required_nr_samples``/``AdaptivePopulationSize.update``, and end-to-end
runs where the CV criterion visibly drives n across generations on the
Gaussian toy — host and device paths.
"""
import jax
import numpy as np
import pandas as pd
import pytest

import pyabc_tpu as pt
from pyabc_tpu.populationstrategy import AdaptivePopulationSize, calc_cv
from pyabc_tpu.transition import MultivariateNormalTransition

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


class _FixedCVTransition:
    """Transition stub whose mean_cv is a known function of n — lets
    calc_cv/bisection be checked against closed forms."""

    NR_BOOTSTRAP = 5

    def __init__(self, cv_fn):
        self.cv_fn = cv_fn
        self.seen_bootstrap = []

    def mean_cv(self, n):
        self.seen_bootstrap.append(self.NR_BOOTSTRAP)
        return self.cv_fn(n)


class TestCalcCV:
    def test_weighted_average_closed_form(self):
        """calc_cv = Σ_m w_m · mean_cv_m (model-weighted bootstrap CV)."""
        t1 = _FixedCVTransition(lambda n: 0.2)
        t2 = _FixedCVTransition(lambda n: 0.6)
        cv = calc_cv(100, np.array([0.25, 0.75]), 7, [t1, t2])
        assert cv == pytest.approx(0.25 * 0.2 + 0.75 * 0.6)

    def test_model_weights_normalized(self):
        t1 = _FixedCVTransition(lambda n: 0.4)
        cv = calc_cv(100, np.array([2.0]), 3, [t1])
        assert cv == pytest.approx(0.4)

    def test_nr_bootstrap_applied_and_restored(self):
        t1 = _FixedCVTransition(lambda n: 0.1)
        t1.NR_BOOTSTRAP = 11
        calc_cv(50, np.array([1.0]), 3, [t1])
        assert t1.seen_bootstrap == [3]  # override active during the call
        assert t1.NR_BOOTSTRAP == 11  # restored afterwards


def _fitted_mvn(n=250, d=2, seed=0):
    rng = np.random.default_rng(seed)
    X = pd.DataFrame(rng.normal(size=(n, d)),
                     columns=[f"p{i}" for i in range(d)])
    w = np.full(n, 1.0 / n)
    tr = MultivariateNormalTransition()
    tr.fit(X, w)
    return tr


class TestMeanCV:
    def test_cv_positive_and_decreasing_in_n(self):
        """Bootstrap CV of the KDE density shrinks as the (re)sample grows
        — the monotonicity AdaptivePopulationSize's bisection relies on."""
        tr = _fitted_mvn()
        tr.NR_BOOTSTRAP = 10
        cv_small = tr.mean_cv(20)
        cv_large = tr.mean_cv(2000)
        assert cv_small > 0
        assert cv_large > 0
        assert cv_large < cv_small

    def test_required_nr_samples_meets_target(self):
        tr = _fitted_mvn()
        target = 1.2 * tr.mean_cv(500)  # reachable target
        n_req = tr.required_nr_samples(target)
        assert tr.mean_cv(n_req) <= target

    def test_required_nr_samples_unreachable_returns_hi(self):
        tr = _fitted_mvn(n=50)
        n_req = tr.required_nr_samples(1e-9)  # unreachably tight
        assert n_req == max(10 * 50, 1000)


class TestAdaptivePopulationSizeUpdate:
    def test_bisection_finds_threshold_n(self):
        """With mean_cv(n) = 1/sqrt(n), target cv c ⇒ n* = ceil(1/c²)."""
        aps = AdaptivePopulationSize(
            start_nr_particles=100, mean_cv=0.1,
            min_population_size=10, max_population_size=10_000,
        )
        tr = _FixedCVTransition(lambda n: 1.0 / np.sqrt(n))
        aps.update([tr], np.array([1.0]), t=0)
        assert aps.nr_particles == 100  # 1/0.1² = 100 exactly

    def test_unreachable_target_caps_at_max(self):
        aps = AdaptivePopulationSize(
            start_nr_particles=100, mean_cv=1e-6,
            min_population_size=10, max_population_size=500,
        )
        tr = _FixedCVTransition(lambda n: 1.0 / np.sqrt(n))
        aps.update([tr], np.array([1.0]), t=0)
        assert aps.nr_particles == 500

    def test_loose_target_floors_at_min(self):
        aps = AdaptivePopulationSize(
            start_nr_particles=100, mean_cv=10.0,
            min_population_size=25, max_population_size=1000,
        )
        tr = _FixedCVTransition(lambda n: 1.0 / np.sqrt(n))
        aps.update([tr], np.array([1.0]), t=0)
        assert aps.nr_particles == 25

    def test_degenerate_transition_keeps_previous_n(self):
        aps = AdaptivePopulationSize(start_nr_particles=77, mean_cv=0.05)

        class _Boom:
            NR_BOOTSTRAP = 5

            def mean_cv(self, n):
                raise pt.NotEnoughParticles("degenerate")

        aps.update([_Boom()], np.array([1.0]), t=0)
        assert aps.nr_particles == 77

    def test_real_mvn_adapts_with_target(self):
        """On a real fitted MVN, a loose target shrinks n and a tight
        target grows it — CV drives the decision in both directions."""
        tr = _fitted_mvn(n=200, d=1, seed=3)
        cv_at_200 = calc_cv(200, np.array([1.0]), 10, [tr])

        loose = AdaptivePopulationSize(
            start_nr_particles=200, mean_cv=3.0 * cv_at_200,
            min_population_size=10, max_population_size=2000,
        )
        loose.update([tr], np.array([1.0]), t=0)
        assert loose.nr_particles < 200

        tight = AdaptivePopulationSize(
            start_nr_particles=200, mean_cv=cv_at_200 / 3.0,
            min_population_size=10, max_population_size=2000,
        )
        tight.update([tr], np.array([1.0]), t=0)
        assert tight.nr_particles > 200


def _gauss_jax_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _posterior_moments(history, m=0, par="theta"):
    df, w = history.get_distribution(m)
    mu = float(np.sum(df[par] * w))
    sd = float(np.sqrt(np.sum(w * (df[par] - mu) ** 2)))
    return mu, sd


def _per_generation_n(history):
    counts = history.get_nr_particles_per_population()
    return counts[counts.index >= 0].to_numpy()


class TestDeviceBootstrapCV:
    def test_device_mean_cv_tracks_host(self):
        """device_mean_cv (traceable bootstrap CV) agrees with the host
        Transition.mean_cv statistically on identical fitted particles."""
        import jax.numpy as jnp

        tr = _fitted_mvn(n=128, d=2, seed=7)
        params = {k: jnp.asarray(v) for k, v in tr.device_params().items()}
        dev = float(MultivariateNormalTransition.device_mean_cv(
            params, jax.random.PRNGKey(0), jnp.asarray(64),
            dim=2, scaling=tr.scaling,
            bandwidth_selector=tr.bandwidth_selector, n_bootstrap=30,
        ))
        tr.NR_BOOTSTRAP = 30
        host = tr.mean_cv(64)
        assert dev > 0
        assert dev == pytest.approx(host, rel=0.5)

    def test_device_cv_decreases_with_n(self):
        import jax.numpy as jnp

        tr = _fitted_mvn(n=128, d=2, seed=7)
        params = {k: jnp.asarray(v) for k, v in tr.device_params().items()}

        def cv(n):
            return float(MultivariateNormalTransition.device_mean_cv(
                params, jax.random.PRNGKey(0), jnp.asarray(n),
                dim=2, scaling=tr.scaling,
                bandwidth_selector=tr.bandwidth_selector, n_bootstrap=20,
            ))

        # n stays within the 128-lane capacity: beyond n_cap the bootstrap
        # degenerates to n_cap draws (production clamps max_n to n_cap)
        assert cv(128) < cv(8)

    def test_device_required_nr_bisection(self):
        """The in-kernel bisection lands where its own CV criterion flips,
        inside [min_n, max_n], and returns max_n for unreachable targets."""
        import jax.numpy as jnp

        tr = _fitted_mvn(n=128, d=2, seed=7)
        params = {k: jnp.asarray(v) for k, v in tr.device_params().items()}
        kw = dict(dim=2, scaling=tr.scaling,
                  bandwidth_selector=tr.bandwidth_selector, n_bootstrap=20)
        key = jax.random.PRNGKey(3)
        cv_at_96 = float(MultivariateNormalTransition.device_mean_cv(
            params, key, jnp.asarray(96), **kw))
        n_req = int(MultivariateNormalTransition.device_required_nr(
            params, key, target_cv=cv_at_96, min_n=10, max_n=128, **kw))
        assert 10 <= n_req <= 128
        cv_found = float(MultivariateNormalTransition.device_mean_cv(
            params, key, jnp.asarray(n_req), **kw))
        assert cv_found <= cv_at_96
        # unreachable target caps at max_n
        n_hi = int(MultivariateNormalTransition.device_required_nr(
            params, key, target_cv=1e-9, min_n=10, max_n=128, **kw))
        assert n_hi == 128


class TestAdaptiveNFused:
    def _aps(self):
        return AdaptivePopulationSize(
            start_nr_particles=150, mean_cv=0.5,
            min_population_size=20, max_population_size=600, n_bootstrap=5,
        )

    def test_capability_gate(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=self._aps(),
                        eps=pt.MedianEpsilon(), seed=11)
        assert abc._fused_chunk_capable()
        # unbounded adaptive growth cannot ride static shapes
        unbounded = AdaptivePopulationSize(start_nr_particles=150,
                                           mean_cv=0.5)
        abc_u = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                          population_size=unbounded,
                          eps=pt.MedianEpsilon(), seed=11)
        assert not abc_u._fused_chunk_capable()
        # LocalTransition rides fused adaptive-n too (round 5): its
        # static k_cap is sized to the adaptive max_population_size
        abc_l = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                          population_size=self._aps(),
                          eps=pt.MedianEpsilon(), seed=11,
                          transitions=pt.LocalTransition())
        assert abc_l._fused_chunk_capable()
        # GridSearchCV stays host-path under adaptive n (its mean_cv
        # delegates to the per-generation winning estimator)
        abc_g = pt.ABCSMC(
            _gauss_jax_model(), prior, pt.PNormDistance(p=2),
            population_size=self._aps(), eps=pt.MedianEpsilon(), seed=11,
            transitions=pt.GridSearchCV(
                pt.MultivariateNormalTransition(),
                {"scaling": [0.5, 1.0]}),
        )
        assert not abc_g._fused_chunk_capable()

    @pytest.mark.slow
    def test_fused_cv_drives_n(self):
        """The fused chunk runs the bootstrap-CV bisection in-kernel; n
        must move off the start size and stay inside the bounds, with the
        host strategy mirroring the device decision."""
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        aps = self._aps()
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=aps, eps=pt.MedianEpsilon(),
                        seed=11, fused_generations=3)
        assert abc._fused_chunk_capable()
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        ns = _per_generation_n(h)
        assert len(ns) >= 3
        assert ns[0] == 150
        assert any(n != 150 for n in ns[1:])
        assert all(20 <= n <= 600 for n in ns)
        # host mirror of the device decision
        assert 20 <= aps.nr_particles <= 600
        mu, _sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.35)

    @pytest.mark.slow
    def test_fused_matches_unfused_direction(self):
        """Fused (in-kernel CV) and unfused (host CV) runs of the same
        config agree on the adaptation direction and the posterior."""
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        runs = {}
        for label, fused_g in (("fused", 3), ("unfused", 1)):
            abc = pt.ABCSMC(_gauss_jax_model(), prior,
                            pt.PNormDistance(p=2),
                            population_size=self._aps(),
                            eps=pt.MedianEpsilon(), seed=17,
                            fused_generations=fused_g)
            abc.new("sqlite://", {"x": X_OBS})
            h = abc.run(max_nr_populations=4)
            runs[label] = (_per_generation_n(h), _posterior_moments(h))
        ns_f, (mu_f, _) = runs["fused"]
        ns_u, (mu_u, _) = runs["unfused"]
        # same direction of adaptation off the start size
        assert np.sign(ns_f[1] - 150) == np.sign(ns_u[1] - 150)
        assert mu_f == pytest.approx(mu_u, abs=0.3)


class TestAdaptiveNFusedWidened:
    """Round-5 widenings of the fused adaptive-n gate (round-4 verdict
    Missing #5): K>1 via model-probability-weighted per-model bootstrap
    CVs, LocalTransition via the generic device CV machinery, and
    GridSearchCV x ListPopulationSize via per-generation fold tables."""

    def _aps(self):
        return AdaptivePopulationSize(
            start_nr_particles=150, mean_cv=0.5,
            min_population_size=20, max_population_size=600, n_bootstrap=5,
        )

    @pytest.mark.slow
    def test_fused_adaptive_n_local_transition(self):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        aps = self._aps()
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=aps, eps=pt.MedianEpsilon(),
                        seed=11, fused_generations=3,
                        transitions=pt.LocalTransition(k_fraction=0.3))
        assert abc._fused_chunk_capable()
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=5)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        ns = _per_generation_n(h)
        assert ns[0] == 150
        assert any(n != 150 for n in ns[1:])
        assert all(20 <= n <= 600 for n in ns)
        mu, _sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.35)

    @pytest.mark.slow
    def test_fused_adaptive_n_multimodel(self):
        """K=2 adaptive-n fused: the in-kernel CV aggregates the two
        models' bootstrap CVs by their current probabilities (reference
        calc_cv), and the model posterior stays correct."""
        from pyabc_tpu.models import model_selection as msel

        models, priors, analytic = msel.tractable_pair()
        x_obs = 0.7
        # keep the floor high enough that neither model goes extinct by
        # chance in a 2-model population (n=20 would)
        aps = AdaptivePopulationSize(
            start_nr_particles=150, mean_cv=0.5,
            min_population_size=100, max_population_size=600,
            n_bootstrap=5,
        )
        abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                        population_size=aps, eps=pt.MedianEpsilon(),
                        seed=23, fused_generations=3)
        assert abc._fused_chunk_capable()
        abc.new("sqlite://", {"x": x_obs})
        h = abc.run(max_nr_populations=5)
        assert h.get_telemetry(2).get("fused_chunk"), "fused path not taken"
        ns = _per_generation_n(h)
        assert any(n != 150 for n in ns[1:])
        assert all(20 <= n <= 600 for n in ns)
        probs = h.get_model_probabilities(h.max_t)["p"]
        expect = analytic(x_obs)
        assert float(probs.get(0, 0.0)) == pytest.approx(expect[0],
                                                         abs=0.3)

    @pytest.mark.slow
    def test_fused_gridsearch_list_population(self):
        """GridSearchCV x ListPopulationSize rides fused chunks with
        per-generation fold tables; particle counts follow the schedule
        and the posterior matches the host path."""
        sched = [200, 260, 150, 220]
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        runs = {}
        for label, fused_g in (("fused", 3), ("host", 1)):
            abc = pt.ABCSMC(
                _gauss_jax_model(), prior, pt.PNormDistance(p=2),
                population_size=pt.ListPopulationSize(sched),
                eps=pt.MedianEpsilon(), seed=31, fused_generations=fused_g,
                transitions=pt.GridSearchCV(
                    pt.MultivariateNormalTransition(),
                    {"scaling": [0.25, 1.0, 2.25]}, cv=5),
            )
            if fused_g > 1:
                assert abc._fused_chunk_capable()
            abc.new("sqlite://", {"x": X_OBS})
            h = abc.run(max_nr_populations=len(sched))
            counts = _per_generation_n(h)
            np.testing.assert_array_equal(counts, sched)
            runs[label] = _posterior_moments(h)
        assert runs["fused"][0] == pytest.approx(runs["host"][0], abs=0.3)


class TestAdaptiveNEndToEnd:
    def test_host_path_cv_drives_n(self):
        """Gaussian toy on the scalar host path: the CV criterion must
        visibly move n away from the start size across generations."""
        rng = np.random.default_rng(0)

        def model(pars):
            return {"x": pars["theta"] + NOISE_SD * rng.normal()}

        import scipy.stats as st

        prior = pt.Distribution(theta=pt.ScipyRV(st.norm(0, PRIOR_SD)))
        np.random.seed(0)
        aps = AdaptivePopulationSize(
            start_nr_particles=150, mean_cv=0.5,
            min_population_size=20, max_population_size=600, n_bootstrap=5,
        )
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=aps,
                        eps=pt.QuantileEpsilon(initial_epsilon=1.0,
                                               alpha=0.5),
                        sampler=pt.SingleCoreSampler())
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=4)
        ns = _per_generation_n(h)
        assert len(ns) >= 2
        assert ns[0] == 150  # first generation uses the start size
        assert any(n != 150 for n in ns[1:])  # CV moved n
        assert all(20 <= n <= 600 for n in ns)
        mu, _sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.35)

    @pytest.mark.slow
    def test_device_unfused_path_cv_drives_n(self):
        """Same criterion on the batched device path (per-generation loop:
        AdaptivePopulationSize's host bisection runs between kernels)."""
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
        aps = AdaptivePopulationSize(
            start_nr_particles=150, mean_cv=0.5,
            min_population_size=20, max_population_size=600, n_bootstrap=5,
        )
        abc = pt.ABCSMC(_gauss_jax_model(), prior, pt.PNormDistance(p=2),
                        population_size=aps, eps=pt.MedianEpsilon(), seed=11)
        assert abc._device_capable
        abc.new("sqlite://", {"x": X_OBS})
        h = abc.run(max_nr_populations=4)
        ns = _per_generation_n(h)
        assert len(ns) >= 2
        assert any(n != 150 for n in ns[1:])
        assert all(20 <= n <= 600 for n in ns)
        mu, _sd = _posterior_moments(h)
        assert mu == pytest.approx(POST_MU, abs=0.35)
