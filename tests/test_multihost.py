"""Two-process multi-host test on localhost CPU.

The reference tests multi-node as multi-process-on-one-host with a real
broker (SURVEY.md §4); here two REAL JAX processes form a distributed
runtime over a localhost coordinator, shard the particle axis over a
2x4-virtual-device global mesh with gloo CPU collectives, and must produce
the correct posterior — proving the per-generation barrier works across
processes (VERDICT r1 #6).
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORKER = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
db_path = sys.argv[3]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
assert jax.process_count() == 2, jax.process_count()
import numpy as np
import pyabc_tpu as pt

NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

mesh = dist.global_mesh()
assert mesh.devices.size == 8
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=200,
                eps=pt.ListEpsilon([1.0, 0.5]), seed=13, mesh=mesh)
abc.new(dist.primary_db(f"sqlite:///{db_path}"), {"x": 1.0})
h = abc.run(max_nr_populations=2)
df, w = h.get_distribution(0)
mu = float(np.sum(df["theta"] * w))
print(f"RESULT pid={pid} mu={mu:.4f} n={len(df)}", flush=True)
"""


WORKER_FUSED = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
db_path = sys.argv[3]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
import numpy as np
import pyabc_tpu as pt

NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

mesh = dist.global_mesh()
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.AdaptivePNormDistance(p=2),
                population_size=200, eps=pt.MedianEpsilon(), seed=13,
                mesh=mesh, fused_generations=3)
abc.new(dist.primary_db(f"sqlite:///{db_path}"), {"x": 1.0})
assert abc._fused_chunk_capable(), "fused chunks must be mesh-capable"
h = abc.run(max_nr_populations=6)
fused = [h.get_telemetry(t).get("fused_chunk") for t in range(h.n_populations)]
assert any(fused), f"chunked loop not taken: {fused}"
df, w = h.get_distribution(0, h.max_t)
mu = float(np.sum(df["theta"] * w))
print(f"RESULT pid={pid} mu={mu:.4f} n={len(df)} gens={h.n_populations}",
      flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_fused_chunks(tmp_path):
    """Fused multi-generation chunks over a TWO-PROCESS global mesh: the
    chunk is the cross-host barrier unit (G generations per DCN sync), and
    both hosts must stay in lock-step through the on-device adaptation."""
    script = tmp_path / "worker_fused.py"
    script.write_text(WORKER_FUSED)
    db = tmp_path / "mh_fused.db"
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), str(db)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT")
    ]
    assert len(results) == 2, outs
    mus = [float(r.split("mu=")[1].split()[0]) for r in results]
    assert mus[0] == pytest.approx(mus[1], abs=1e-6)
    assert mus[0] == pytest.approx(0.8, abs=0.3)
    assert db.exists()


@pytest.mark.slow
def test_two_process_posterior(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    db = tmp_path / "mh.db"
    port = _free_port()
    env = dict(os.environ)
    # the workers pick their own platform via jax.config (NOT env: the
    # conftest env of the pytest process must not leak a single-device cpu)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), str(db)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT")
    ]
    assert len(results) == 2, outs
    mus = [float(r.split("mu=")[1].split()[0]) for r in results]
    # both hosts computed the SAME posterior (lock-step SPMD) ...
    assert mus[0] == pytest.approx(mus[1], abs=1e-6)
    # ... and it is the right one (conjugate posterior mean 0.8, sd 0.447)
    assert mus[0] == pytest.approx(0.8, abs=0.3)
    # only the primary wrote the real db
    assert db.exists()
