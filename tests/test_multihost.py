"""Two-process multi-host test on localhost CPU.

The reference tests multi-node as multi-process-on-one-host with a real
broker (SURVEY.md §4); here two REAL JAX processes form a distributed
runtime over a localhost coordinator, shard the particle axis over a
2x4-virtual-device global mesh with gloo CPU collectives, and must produce
the correct posterior — proving the per-generation barrier works across
processes (VERDICT r1 #6).
"""
import hashlib
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# the CI `multihost` job runs exactly this module (2-process gloo rig on
# localhost, 4 virtual CPU devices per process); the fast distributed-
# module tests (initialize guards, clock offset) ride along in tier-1
pytestmark = pytest.mark.multihost

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORKER = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
db_path = sys.argv[3]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
assert jax.process_count() == 2, jax.process_count()
import numpy as np
import pyabc_tpu as pt

NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

mesh = dist.global_mesh()
assert mesh.devices.size == 8
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=200,
                eps=pt.ListEpsilon([1.0, 0.5]), seed=13, mesh=mesh)
abc.new(dist.primary_db(f"sqlite:///{db_path}"), {"x": 1.0})
h = abc.run(max_nr_populations=2)
df, w = h.get_distribution(0)
mu = float(np.sum(df["theta"] * w))
print(f"RESULT pid={pid} mu={mu:.4f} n={len(df)}", flush=True)
"""


WORKER_FUSED = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
db_path = sys.argv[3]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
import numpy as np
import pyabc_tpu as pt

NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

mesh = dist.global_mesh()
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.AdaptivePNormDistance(p=2),
                population_size=200, eps=pt.MedianEpsilon(), seed=13,
                mesh=mesh, fused_generations=3)
abc.new(dist.primary_db(f"sqlite:///{db_path}"), {"x": 1.0})
assert abc._fused_chunk_capable(), "fused chunks must be mesh-capable"
h = abc.run(max_nr_populations=6)
fused = [h.get_telemetry(t).get("fused_chunk") for t in range(h.n_populations)]
assert any(fused), f"chunked loop not taken: {fused}"
df, w = h.get_distribution(0, h.max_t)
mu = float(np.sum(df["theta"] * w))
print(f"RESULT pid={pid} mu={mu:.4f} n={len(df)} gens={h.n_populations}",
      flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_fused_chunks(tmp_path):
    """Fused multi-generation chunks over a TWO-PROCESS global mesh: the
    chunk is the cross-host barrier unit (G generations per DCN sync), and
    both hosts must stay in lock-step through the on-device adaptation."""
    script = tmp_path / "worker_fused.py"
    script.write_text(WORKER_FUSED)
    db = tmp_path / "mh_fused.db"
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), str(db)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT")
    ]
    assert len(results) == 2, outs
    mus = [float(r.split("mu=")[1].split()[0]) for r in results]
    assert mus[0] == pytest.approx(mus[1], abs=1e-6)
    assert mus[0] == pytest.approx(0.8, abs=0.3)
    assert db.exists()


@pytest.mark.slow
def test_two_process_posterior(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    db = tmp_path / "mh.db"
    port = _free_port()
    env = dict(os.environ)
    # the workers pick their own platform via jax.config (NOT env: the
    # conftest env of the pytest process must not leak a single-device cpu)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), str(db)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT")
    ]
    assert len(results) == 2, outs
    mus = [float(r.split("mu=")[1].split()[0]) for r in results]
    # both hosts computed the SAME posterior (lock-step SPMD) ...
    assert mus[0] == pytest.approx(mus[1], abs=1e-6)
    # ... and it is the right one (conjugate posterior mean 0.8, sd 0.447)
    assert mus[0] == pytest.approx(0.8, abs=0.3)
    # only the primary wrote the real db
    assert db.exists()


# ------------------------------------------------- round 18: sharded kernel
#
# The tentpole claim: the shard_map multigen kernel runs UNCHANGED over a
# multi-process global mesh — shard-local segment sweeps per host, scalar
# columns all-gathered over DCN — and is BIT-identical (every generation's
# thetas, weights and the epsilon trail) to the 1-process virtual-shard
# reference at the same shard count. The workers print a sha256 digest
# over the full History; the test compares digests across proc0, proc1
# and the solo reference.

#: digest body shared by every worker below (and mirrored by
#: ``_digest_history`` for in-process references) — epsilon trail plus
#: every generation's (theta, weight) float64 bytes
_DIGEST_SRC = """
def _digest(h, sort_rows=False):
    import hashlib
    import numpy as np
    pops = h.get_all_populations().query("t >= 0")
    dig = hashlib.sha256()
    dig.update(pops["epsilon"].to_numpy().astype(np.float64).tobytes())
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        th = df.to_numpy().astype(np.float64)
        w = np.asarray(w, np.float64)
        if sort_rows:
            order = np.lexsort(th.T)
            th, w = th[order], w[order]
        dig.update(th.tobytes())
        dig.update(w.tobytes())
    eps = ",".join(f"{e:.10g}" for e in pops["epsilon"])
    return dig.hexdigest(), eps
"""

exec(_DIGEST_SRC)  # defines _digest for in-process references


def _spawn_workers(script_text, tmp_path, extra_args=(), n_procs=2,
                   timeout=420):
    """Run ``n_procs`` copies of a worker script (argv: pid, port,
    *extra_args) against one fresh coordinator port; returns the RESULT
    lines (one per process, order proc0..procN)."""
    script = tmp_path / "mh_worker.py"
    script.write_text(script_text)
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port),
             *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(n_procs)
    ]
    outs = [p.communicate(timeout=timeout)[0] for p in procs]
    failures = [f"proc {pid} rc={p.returncode}:\n{out[-3000:]}"
                for pid, (p, out) in enumerate(zip(procs, outs))
                if p.returncode != 0]
    assert not failures, "\n\n".join(failures)
    results = []
    for pid, out in enumerate(outs):
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
        assert len(lines) == 1, f"proc {pid}:\n{out[-3000:]}"
        results.append(lines[0])
    return results


def _field(line, key):
    return line.split(f"{key}=")[1].split()[0]


WORKER_SHARDED = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
import numpy as np
import pyabc_tpu as pt
""" + _DIGEST_SRC + """
NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss_mh")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

mesh = dist.global_mesh()
assert mesh.devices.size == 8
prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=128,
                eps=pt.MedianEpsilon(), seed=21, mesh=mesh, sharded=8,
                fused_generations=3)
assert abc._sharded_n() == 8
abc.new(dist.primary_db("sqlite://"), {"x": 1.0})
h = abc.run(max_nr_populations=4)
rep = abc._engine.sync_budget_report()
digest, eps = _digest(h)
print(f"RESULT pid={pid} digest={digest} eps=[{eps}]"
      f" syncs={rep['syncs']} chunks={rep['chunks']} ok={rep['ok']}",
      flush=True)
"""


WORKER_SHARDED_REF = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import pyabc_tpu as pt
""" + _DIGEST_SRC + """
NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss_mh")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=128,
                eps=pt.MedianEpsilon(), seed=21, sharded=8,
                fused_generations=3)
abc.new("sqlite://", {"x": 1.0})
h = abc.run(max_nr_populations=4)
digest, eps = _digest(h)
print(f"RESULT pid=ref digest={digest} eps=[{eps}]", flush=True)
"""


def _run_reference(script_text, tmp_path, name="mh_ref.py"):
    script = tmp_path / name
    script.write_text(script_text)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("RESULT")]
    assert len(lines) == 1, proc.stdout[-3000:]
    return lines[0]


@pytest.mark.slow
def test_two_process_sharded_bit_identical(tmp_path):
    """Tentpole acceptance: the sharded multigen kernel on a 2-process
    gloo mesh (2x4 devices, width 8) is BIT-identical — full History
    digest and epsilon trail — to the 1-process virtual-shard run at the
    same shard count, and the strict per-run sync budget holds with the
    collectives spanning processes (syncs_per_run <= chunks + O(1))."""
    results = _spawn_workers(WORKER_SHARDED, tmp_path)
    digests = {_field(r, "digest") for r in results}
    assert len(digests) == 1, results
    ref = _run_reference(WORKER_SHARDED_REF, tmp_path)
    assert _field(ref, "digest") in digests, (ref, results)
    assert _field(ref, "eps") == _field(results[0], "eps")
    for r in results:
        assert _field(r, "ok") == "True", r
        syncs, chunks = int(_field(r, "syncs")), int(_field(r, "chunks"))
        assert syncs <= chunks + 8, r


WORKER_SEGMENTED = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
import numpy as np
import pyabc_tpu as pt
from pyabc_tpu.models import gillespie as g
""" + _DIGEST_SRC + """
model = g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5)
obs = g.observed_birth_death(n_leaps=100, n_obs=20, segments=5)
abc = pt.ABCSMC(model, g.birth_death_prior(), pt.PNormDistance(p=2),
                population_size=64, eps=pt.MedianEpsilon(), seed=41,
                early_reject="auto", mesh=dist.global_mesh(), sharded=8,
                fused_generations=2)
abc.new(dist.primary_db("sqlite://"), obs)
h = abc.run(max_nr_populations=4)
retired = sum((h.get_telemetry(t) or {}).get("retired_early", 0)
              for t in range(h.n_populations))
digest, eps = _digest(h, sort_rows=True)
print(f"RESULT pid={pid} digest={digest} eps=[{eps}] retired={retired}",
      flush=True)
"""


WORKER_SEGMENTED_REF = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import pyabc_tpu as pt
from pyabc_tpu.models import gillespie as g
""" + _DIGEST_SRC + """
model = g.make_birth_death_model(n_leaps=100, n_obs=20, segments=5)
obs = g.observed_birth_death(n_leaps=100, n_obs=20, segments=5)
abc = pt.ABCSMC(model, g.birth_death_prior(), pt.PNormDistance(p=2),
                population_size=64, eps=pt.MedianEpsilon(), seed=41,
                early_reject="auto", sharded=8, fused_generations=2)
abc.new("sqlite://", obs)
h = abc.run(max_nr_populations=4)
retired = sum((h.get_telemetry(t) or {}).get("retired_early", 0)
              for t in range(h.n_populations))
digest, eps = _digest(h, sort_rows=True)
print(f"RESULT pid=ref digest={digest} eps=[{eps}] retired={retired}",
      flush=True)
"""


@pytest.mark.slow
def test_two_process_segmented_early_reject_bit_identical(tmp_path):
    """The COMPOSED kernel (ISSUE 17's segmented early-reject engine
    inside the sharded kernel) crosses the process boundary too: the
    2-process run retires lanes early and still lands digest-identical
    on the 1-process virtual-shard reference."""
    results = _spawn_workers(WORKER_SEGMENTED, tmp_path)
    digests = {_field(r, "digest") for r in results}
    assert len(digests) == 1, results
    ref = _run_reference(WORKER_SEGMENTED_REF, tmp_path,
                         name="mh_seg_ref.py")
    assert _field(ref, "digest") in digests, (ref, results)
    # early reject genuinely ON in both rigs, retiring the same lanes
    assert int(_field(ref, "retired")) > 0
    assert {_field(r, "retired") for r in results} \
        == {_field(ref, "retired")}


# ---------------------------------------- preempt/resume across topologies
#
# PR-5 checkpoints are written by the PRIMARY only and adoptable at any
# process count x width: a run interrupted on a 1-process virtual-shard
# topology resumes on the 2-process mesh (each non-primary loading a
# private COPY of the primary's sqlite file via ``resume_db``) and lands
# bit-identical on the uninterrupted solo run.

WORKER_RESUME = """
import sys
pid = int(sys.argv[1])
port = sys.argv[2]
db_path = sys.argv[3]
ck = sys.argv[4]
abc_id = int(sys.argv[5])
from pyabc_tpu.parallel import distributed as dist
dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
import jax
import numpy as np
import pyabc_tpu as pt
""" + _DIGEST_SRC + """
NOISE_SD = 0.5

@pt.JaxModel.from_function(["theta"], name="gauss_mh_resume")
def model(key, theta):
    return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2), population_size=64,
                eps=pt.MedianEpsilon(), seed=21, mesh=dist.global_mesh(),
                sharded=8, fused_generations=2, checkpoint_path=ck)
abc.load(dist.resume_db(f"sqlite:///{db_path}"), abc_id)
h = abc.run(max_nr_populations=4)
digest, eps = _digest(h)
print(f"RESULT pid={pid} digest={digest} eps=[{eps}]"
      f" gens={h.n_populations}", flush=True)
"""


@pytest.mark.slow
def test_preempt_virtual_resume_two_process_bit_identical(tmp_path):
    """Interrupt a 1-process virtual-shard run at the first chunk
    boundary (the production graceful-stop path), resume it on the
    2-process global mesh — both processes adopt the primary-written
    checkpoint and finish bit-identical to the uninterrupted solo run."""
    import jax

    import pyabc_tpu as pt
    from pyabc_tpu.inference.smc import GracefulShutdown

    NOISE_SD = 0.5

    def make(checkpoint_path=None):
        @pt.JaxModel.from_function(["theta"], name="gauss_mh_resume")
        def model(key, theta):
            return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

        return pt.ABCSMC(
            model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
            pt.PNormDistance(p=2), population_size=64,
            eps=pt.MedianEpsilon(), seed=21, sharded=8,
            fused_generations=2, checkpoint_path=checkpoint_path)

    # the uninterrupted solo reference
    ref = make()
    ref.new("sqlite://", {"x": 1.0})
    h_ref = ref.run(max_nr_populations=4)
    ref_digest, ref_eps = _digest(h_ref)

    # interrupt at the first chunk boundary
    db_path = tmp_path / "mh_resume.db"
    ck = tmp_path / "mh_resume.ck"
    abc = make(checkpoint_path=str(ck))
    abc.new(f"sqlite:///{db_path}", {"x": 1.0})
    abc_id = int(abc.history.id)
    abc.chunk_event_cb = lambda ev: abc.request_graceful_stop()
    with pytest.raises(GracefulShutdown):
        abc.run(max_nr_populations=4)
    assert 0 < abc.history.n_populations < 4
    assert ck.exists()

    # resume on the 2-process mesh
    results = _spawn_workers(WORKER_RESUME, tmp_path,
                             extra_args=(db_path, ck, abc_id))
    for r in results:
        assert _field(r, "gens") == "4", r
        assert _field(r, "digest") == ref_digest, (r, ref_digest)
        assert _field(r, "eps") == f"[{ref_eps}]", r
    # the non-primary resumed from a private COPY, never the real file
    assert (tmp_path / "mh_resume.db.proc1").exists()


# -------------------------------------------------- fast distributed tests
#
# No subprocesses, no jax.distributed: the initialize() config guards and
# the cross-process clock-offset rig are plain-python testable and run in
# tier-1.

class TestInitializeGuards:
    @pytest.fixture(autouse=True)
    def _clean_env(self, monkeypatch):
        for var in ("PYABC_TPU_COORDINATOR", "PYABC_TPU_NUM_PROCESSES",
                    "PYABC_TPU_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)

    def test_partial_explicit_config_is_typed_error(self):
        from pyabc_tpu.parallel import distributed as dist

        with pytest.raises(dist.DistributedConfigError, match="missing"):
            dist.initialize("127.0.0.1:12345")

    def test_partial_env_config_is_typed_error(self, monkeypatch):
        from pyabc_tpu.parallel import distributed as dist

        monkeypatch.setenv("PYABC_TPU_COORDINATOR", "127.0.0.1:12345")
        with pytest.raises(dist.DistributedConfigError,
                           match="PYABC_TPU_NUM_PROCESSES"):
            dist.initialize()

    def test_env_fallback_fills_the_triple(self, monkeypatch):
        from pyabc_tpu.parallel import distributed as dist

        monkeypatch.setenv("PYABC_TPU_COORDINATOR", "127.0.0.1:12345")
        monkeypatch.setenv("PYABC_TPU_NUM_PROCESSES", "2")
        monkeypatch.setenv("PYABC_TPU_PROCESS_ID", "1")
        cfg = dist._resolve_init_config(
            None, None, None, platform=None, num_cpu_devices=None,
            cpu_collectives="gloo")
        assert cfg["coordinator_address"] == "127.0.0.1:12345"
        assert cfg["num_processes"] == 2
        assert cfg["process_id"] == 1

    def test_second_identical_initialize_is_noop(self, monkeypatch):
        from pyabc_tpu.parallel import distributed as dist

        cfg = dist._resolve_init_config(
            "127.0.0.1:1", 2, 0, platform="cpu", num_cpu_devices=4,
            cpu_collectives="gloo")
        monkeypatch.setattr(dist, "_INIT_CONFIG", cfg)
        # same config: returns before touching jax.config or the runtime
        # (a real re-init attempt against 127.0.0.1:1 would error out)
        dist.initialize("127.0.0.1:1", 2, 0, platform="cpu",
                        num_cpu_devices=4)

    def test_conflicting_reinitialize_is_typed_error(self, monkeypatch):
        from pyabc_tpu.parallel import distributed as dist

        cfg = dist._resolve_init_config(
            "127.0.0.1:1", 2, 0, platform="cpu", num_cpu_devices=4,
            cpu_collectives="gloo")
        monkeypatch.setattr(dist, "_INIT_CONFIG", cfg)
        with pytest.raises(dist.DistributedConfigError,
                           match="re-initialized"):
            dist.initialize("127.0.0.1:1", 2, 1, platform="cpu",
                            num_cpu_devices=4)


class TestClockOffset:
    def test_offset_measured_within_rtt_bound_and_recorded(self):
        """NTP-style probe against a second 'host' serving its monotonic
        clock over TCP: on one machine CLOCK_MONOTONIC shares its epoch,
        so the measured offset must sit inside the +-RTT/2 uncertainty
        window — the bound the span-merge contract leans on. The summary
        lands per-host in the observability snapshot."""
        from pyabc_tpu import observability
        from pyabc_tpu.parallel import distributed as dist

        port, stop = dist.serve_clock()
        try:
            est = dist.measure_clock_offset(
                f"127.0.0.1:{port}", host="host-b")
        finally:
            stop()
        s = est.summary()
        assert s["n_samples"] == 16
        assert s["rtt_s"] > 0.0
        assert abs(s["offset_s"]) <= s["uncertainty_s"]
        snap = observability.observability_snapshot()
        assert snap["hosts"]["host-b"]["offset_s"] == s["offset_s"]
        assert snap["hosts"]["host-b"]["uncertainty_s"] \
            == s["uncertainty_s"]

    def test_serve_clock_answers_repeated_probes(self):
        from pyabc_tpu.parallel import distributed as dist

        port, stop = dist.serve_clock()
        try:
            a = dist.measure_clock_offset(f"127.0.0.1:{port}",
                                          n_samples=4)
            b = dist.measure_clock_offset(f"127.0.0.1:{port}",
                                          n_samples=4)
        finally:
            stop()
        assert a.summary()["n_samples"] == 4
        assert b.summary()["n_samples"] == 4


# ================================================= round 22: federation
WORKER_FLIGHT = """
import os
import sys
import time

pid = int(sys.argv[1])
port = sys.argv[2]
sink_port = int(sys.argv[3])
clock_port = int(sys.argv[4])
workdir = sys.argv[5]

from pyabc_tpu.parallel import distributed as dist

dist.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                platform="cpu", num_cpu_devices=4)
from pyabc_tpu import observability as obs
from pyabc_tpu.observability import Tracer, read_flight, render_timeline
from pyabc_tpu.parallel.distributed import (
    SpanShipper, measure_clock_offset, serve_clock, serve_span_sink)

done_file = os.path.join(workdir, "primary_done")

if pid == 1:
    # the non-primary host: serve our clock for the primary's offset
    # probe, then emit heartbeat spans and ship them on a steady
    # cadence for the primary's whole chaos run
    _, cstop = serve_clock(clock_port)
    tracer = Tracer()
    shipper = SpanShipper(f"127.0.0.1:{sink_port}", host="h1",
                          process_id=1, tracer=tracer)
    dist.barrier("flight_rig_up")  # the primary's span sink is open
    n = 0
    while not os.path.exists(done_file) and n < 6000:
        with tracer.span("host1_heartbeat", seq=n):
            time.sleep(0.05)
        shipper.ship()
        n += 1
    shipper.ship()
    shipper.close()
    cstop()
    assert shipper.n_shipped >= n, (shipper.n_shipped, n)
    print(f"RESULT pid=1 spans={n} shipped={shipper.n_shipped}")
else:
    from pyabc_tpu.serving import COMPLETED, RunScheduler, TenantSpec

    _, sstop = serve_span_sink(sink_port)
    dist.barrier("flight_rig_up")
    measure_clock_offset(f"127.0.0.1:{clock_port}", host="h1")
    assert "h1" in obs.host_clocks_snapshot()

    # a LONG lease: this 1-core box runs two interpreters, the sink and
    # pytest — a compile-bearing chunk can silently exceed the default
    # lease window, and a lease reap here would overwrite the host_lost
    # flight dump this test exists to assert
    sched = RunScheduler(n_devices=2, n_hosts=2, lease_timeout_s=600.0,
                         base_dir=os.path.join(workdir, "serve"))
    spec = TenantSpec(model="gaussian", population_size=2000,
                      generations=8, seed=91, fused_generations=2)
    t = sched.submit(spec, tenant_id="t-victim")
    # grab the placement WHILE the run holds it: a terminal tenant has
    # released its sub-mesh (submesh_lo is None again), so the loss
    # must be injected mid-flight
    t0 = time.monotonic()
    lo = None
    while time.monotonic() - t0 < 240:
        if lo is None and t.submesh_lo is not None:
            lo = t.submesh_lo
        if lo is not None and t.generations_done >= 1:
            break
        time.sleep(0.02)
    assert lo is not None and t.generations_done >= 1, (t.state, t.error)
    victim_host = lo // sched.allocator.devices_per_host
    affected = sched.mark_host_lost(victim_host)
    assert t.id in affected, (affected, victim_host)
    t0 = time.monotonic()
    while t.state != COMPLETED and time.monotonic() - t0 < 240:
        time.sleep(0.1)
    assert t.state == COMPLETED, (t.state, t.error)
    assert t.device_loss_requeues == 1 and t.requeues == 0, (
        t.device_loss_requeues, t.requeues)
    time.sleep(1.0)  # let the heartbeat tail land in the sink

    # THE fault-path artifact: host loss left a parseable flight file
    payload = read_flight(t.flight_path)
    assert payload["run_id"] == "t-victim"
    assert payload["reason"] == "host_lost", payload["reason"]
    ev_kinds = [e["kind"] for e in payload["events"]]
    assert "host_lost" in ev_kinds and "requeued" in ev_kinds
    assert any(e["kind"] == "host_lost" for e in payload["entries"])
    assert payload["hosts"]["h1"]["offset_s"] is not None
    fed = payload["federated_spans"]
    assert fed, "no federated spans in the fault dump"
    assert all(s["thread"] == "host:1" for s in fed)
    assert all("offset_corrected" not in s["attrs"] for s in fed)
    loc = payload["spans"]
    assert loc, "no local spans in the fault dump"
    assert not any(str(s["thread"]).startswith("host:") for s in loc)

    # merged, offset-corrected coverage: host-1 spans bracket the
    # detection -> reap -> requeue window on the PRIMARY's clock. The
    # federated block is a bounded TAIL, so by completion the
    # pre-detection spans have rolled out of a fresh snapshot — the
    # bracketing uses the DUMP (written at the requeue instant, so its
    # tail reaches back past the detection) for the front edge and a
    # post-completion snapshot for the back edge.
    detect_ts = next(e["ts"] for e in payload["events"]
                     if e["kind"] == "host_lost")
    requeue_ts = next(e["ts"] for e in payload["events"]
                      if e["kind"] == "requeued")
    assert detect_ts <= requeue_ts
    assert min(s["start"] for s in fed) <= detect_ts, (
        min(s["start"] for s in fed), detect_ts)
    snap = t.flight.snapshot(reason="postmortem")
    fed2 = snap["federated_spans"]
    assert fed2 and max(s["end"] for s in fed2) >= requeue_ts, (
        len(fed2), requeue_ts)
    text = render_timeline(payload)
    assert "host:1" in text and "host_lost" in text and "h1" in text

    with open(done_file, "w") as f:
        f.write("done")
    sched.shutdown()
    sstop()
    print(f"RESULT pid=0 state={t.state} "
          f"requeues={t.device_loss_requeues} fed={len(fed)} flight=ok")
"""


@pytest.mark.slow
def test_host_lost_flight_file_federates_both_hosts(tmp_path):
    """Round 22 acceptance: an injected ``host_lost`` on the 2-process
    gloo rig leaves a parseable flight file on the PRIMARY whose
    merged, offset-corrected timeline holds spans from BOTH hosts
    covering detection -> reap -> requeue. Process 1 streams heartbeat
    spans through the federation sink the whole time; process 0 runs
    the scheduler chaos and asserts the artifact end-to-end."""
    sink_port, clock_port = _free_port(), _free_port()
    results = _spawn_workers(
        WORKER_FLIGHT, tmp_path,
        extra_args=(sink_port, clock_port, str(tmp_path)),
        timeout=540)
    assert _field(results[0], "state") == "completed"
    assert _field(results[0], "flight") == "ok"
    assert int(_field(results[0], "fed")) >= 1
    assert int(_field(results[1], "shipped")) >= 1
