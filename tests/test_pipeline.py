"""Cross-generation pipelining (look-ahead analog) tests.

The pipelined loop must be statistically IDENTICAL to the serial loop:
proposals are built from final generation-t weights (unlike the reference's
preliminary-weight Redis look-ahead), so same seed => same posterior.
"""
import jax
import numpy as np
import pytest

import pyabc_tpu as pt

PRIOR_SD = 1.0
NOISE_SD = 0.5
X_OBS = 1.0
POST_VAR = 1.0 / (1 / PRIOR_SD**2 + 1 / NOISE_SD**2)
POST_MU = POST_VAR * (X_OBS / NOISE_SD**2)


def _gauss_model():
    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + NOISE_SD * jax.random.normal(key)}

    return model


def _run(pipeline: bool):
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    # fused_generations=1: this file tests the PER-GENERATION pipelined
    # loop specifically (the fused chunk loop has its own test_fused.py)
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=300,
                    eps=pt.ListEpsilon([1.0, 0.5, 0.3]),
                    seed=31, pipeline=pipeline, fused_generations=1)
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=3)
    df, w = h.get_distribution(0)
    return h, df, w


def test_pipelined_identical_to_serial():
    """Same seed: byte-identical particle sets, not merely close."""
    h_p, df_p, w_p = _run(pipeline=True)
    h_s, df_s, w_s = _run(pipeline=False)
    assert h_p.n_populations == h_s.n_populations
    np.testing.assert_allclose(
        np.sort(df_p["theta"].to_numpy()),
        np.sort(df_s["theta"].to_numpy()), rtol=1e-6,
    )
    np.testing.assert_allclose(np.sort(w_p), np.sort(w_s), rtol=1e-5)


def test_pipelined_posterior_and_telemetry():
    h, df, w = _run(pipeline=True)
    mu = float(np.sum(df["theta"] * w))
    assert mu == pytest.approx(POST_MU, abs=0.25)
    tel = h.get_telemetry(h.max_t)
    assert tel.get("pipelined") is True
    assert {"sample_s", "adapt_s", "persist_s"} <= set(tel)


def test_pipelined_respects_min_acceptance_stop():
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, PRIOR_SD))
    abc = pt.ABCSMC(_gauss_model(), prior, pt.PNormDistance(p=2),
                    population_size=100,
                    eps=pt.ListEpsilon([1.0, 0.01, 0.001]), seed=32)
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=3, min_acceptance_rate=0.05)
    # tiny eps forces an acceptance collapse; the loop must stop early
    assert h.n_populations < 3
